"""Checkpointing: save/load params, persistables, inference models.

Reference: python/paddle/fluid/io.py (save_vars :130, save_params :263,
save_persistables :496, load_vars :546, save_inference_model :965,
load_inference_model :1157). The reference emits save/load OPS and runs a
save program; here the executor scope already holds jax arrays, so
checkpointing is a direct (sharding-aware) serialization of scope state plus
the serialized Program — the orbax-style pytree checkpoint in fluid clothing.
"""
from __future__ import annotations

import json
import os
from typing import List, Optional

import numpy as np

from .executor import Scope, global_scope
from .framework import Parameter, Program, Variable

__all__ = ["save_vars", "save_params", "save_persistables", "load_vars",
           "load_params", "load_persistables", "save_inference_model",
           "load_inference_model", "save_checkpoint", "load_checkpoint"]

_MANIFEST = "manifest.json"


def _vars_of(program: Program, predicate) -> List[Variable]:
    return [v for v in program.list_vars() if predicate(v)]


def _save_var_list(executor, dirname: str, vars_: List[Variable],
                   scope: Optional[Scope], filename: Optional[str]):
    scope = scope or global_scope()
    os.makedirs(dirname, exist_ok=True)
    manifest = {}
    blobs = {}
    for v in vars_:
        val = scope.find_var(v.name)
        if val is None:
            raise RuntimeError(f"save: variable '{v.name}' has no value in scope")
        arr = np.asarray(val)
        blobs[v.name] = arr
        manifest[v.name] = {"shape": list(arr.shape), "dtype": str(arr.dtype)}
    if filename is None:
        for name, arr in blobs.items():
            np.save(os.path.join(dirname, name.replace("/", "__") + ".npy"),
                    arr, allow_pickle=False)
    else:
        # combined blob is an npz archive (plain tensor bytes, never pickled
        # objects — loading an untrusted checkpoint must not execute code).
        # Passed as a file object so np.savez keeps the exact filename.
        with open(os.path.join(dirname, filename), "wb") as f:
            np.savez(f, **{n.replace("/", "__"): a for n, a in blobs.items()})
    with open(os.path.join(dirname, _MANIFEST), "w") as f:
        json.dump({"vars": manifest, "filename": filename}, f)


def _load_var_list(executor, dirname: str, vars_: List[Variable],
                   scope: Optional[Scope], filename: Optional[str]):
    import jax.numpy as jnp

    scope = scope or global_scope()
    manifest_path = os.path.join(dirname, _MANIFEST)
    blobs = {}
    if filename is not None or (os.path.exists(manifest_path) and
                                json.load(open(manifest_path)).get("filename")):
        fname = filename or json.load(open(manifest_path))["filename"]
        with np.load(os.path.join(dirname, fname),
                     allow_pickle=False) as combined:
            wanted = {v.name.replace("/", "__"): v.name for v in vars_}
            for key, name in wanted.items():
                if key not in combined:
                    raise RuntimeError(
                        f"load: '{name}' missing from checkpoint")
                blobs[name] = combined[key]
    for v in vars_:
        if blobs:
            arr = blobs[v.name]
        else:
            path = os.path.join(dirname, v.name.replace("/", "__") + ".npy")
            if not os.path.exists(path):
                raise RuntimeError(f"load: '{path}' not found")
            arr = np.load(path)
        if v.shape is not None and tuple(arr.shape) != tuple(v.shape) \
                and -1 not in (v.shape or ()):
            raise RuntimeError(
                f"load: shape mismatch for '{v.name}': checkpoint "
                f"{arr.shape} vs program {v.shape}")
        scope.set_var(v.name, jnp.asarray(arr))


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None, scope=None):
    from .framework import default_main_program

    program = main_program or default_main_program()
    if vars is None:
        vars = _vars_of(program, predicate or (lambda v: v.persistable))
    _save_var_list(executor, dirname, vars, scope, filename)


def save_params(executor, dirname, main_program=None, filename=None,
                scope=None):
    save_vars(executor, dirname, main_program,
              predicate=lambda v: isinstance(v, Parameter), filename=filename,
              scope=scope)


def save_persistables(executor, dirname, main_program=None, filename=None,
                      scope=None):
    save_vars(executor, dirname, main_program,
              predicate=lambda v: v.persistable, filename=filename,
              scope=scope)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None, scope=None):
    from .framework import default_main_program

    program = main_program or default_main_program()
    if vars is None:
        vars = _vars_of(program, predicate or (lambda v: v.persistable))
    _load_var_list(executor, dirname, vars, scope, filename)


def load_params(executor, dirname, main_program=None, filename=None,
                scope=None):
    load_vars(executor, dirname, main_program,
              predicate=lambda v: isinstance(v, Parameter), filename=filename,
              scope=scope)


def load_persistables(executor, dirname, main_program=None, filename=None,
                      scope=None):
    load_vars(executor, dirname, main_program,
              predicate=lambda v: v.persistable, filename=filename,
              scope=scope)


# ---------------------------------------------------------------------------
# Inference model export (reference io.py:965 prunes to feed/fetch + saves)
# ---------------------------------------------------------------------------

def _prune_for_inference(program: Program, feed_names, fetch_names) -> Program:
    """Keep only ops on the path from feeds to fetches (reference Prune,
    framework/prune.cc)."""
    pruned = program.clone(for_test=True)
    block = pruned.global_block
    needed = set(fetch_names)
    keep = []
    for op in reversed(block.ops):
        if any(n in needed for n in op.output_arg_names):
            keep.append(op)
            needed.update(op.input_arg_names)
    block.ops = list(reversed(keep))
    used = set()
    for op in block.ops:
        used.update(op.input_arg_names)
        used.update(op.output_arg_names)
    used.update(feed_names)
    used.update(fetch_names)
    block.vars = {n: v for n, v in block.vars.items() if n in used}
    return pruned


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, scope=None):
    from .framework import default_main_program

    program = main_program or default_main_program()
    fetch_names = [t.name if isinstance(t, Variable) else t
                   for t in target_vars]
    pruned = _prune_for_inference(program, feeded_var_names, fetch_names)
    os.makedirs(dirname, exist_ok=True)
    model_filename = model_filename or "__model__"
    with open(os.path.join(dirname, model_filename), "w") as f:
        json.dump({"program": pruned.to_dict(),
                   "feed_names": list(feeded_var_names),
                   "fetch_names": fetch_names}, f)
    params = [v for v in pruned.list_vars() if v.persistable]
    _save_var_list(executor, os.path.join(dirname, "params"), params, scope,
                   params_filename)
    return fetch_names


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None, scope=None):
    model_filename = model_filename or "__model__"
    with open(os.path.join(dirname, model_filename)) as f:
        meta = json.load(f)
    program = Program.from_dict(meta["program"])
    params = [v for v in program.list_vars() if v.persistable]
    _load_var_list(executor, os.path.join(dirname, "params"), params, scope,
                   params_filename)
    fetch_vars = [program.global_block.var(n) for n in meta["fetch_names"]]
    return program, meta["feed_names"], fetch_vars


# convenience full-checkpoint helpers (beyond the reference: adds step/meta)
def save_checkpoint(executor, dirname, main_program=None, scope=None,
                    meta: dict = None):
    save_persistables(executor, dirname, main_program, filename="ckpt.npz",
                      scope=scope)
    with open(os.path.join(dirname, "meta.json"), "w") as f:
        json.dump(meta or {}, f)


def load_checkpoint(executor, dirname, main_program=None, scope=None) -> dict:
    load_persistables(executor, dirname, main_program, filename="ckpt.npz",
                      scope=scope)
    meta_path = os.path.join(dirname, "meta.json")
    return json.load(open(meta_path)) if os.path.exists(meta_path) else {}


# reference fluid.io re-exports the data pipeline (python/paddle/fluid/io.py
# pulls DataLoader/PyReader from reader.py)
from .reader import DataLoader, PyReader  # noqa: E402,F401
