"""Checkpointing: save/load params, persistables, inference models.

Reference: python/paddle/fluid/io.py (save_vars :130, save_params :263,
save_persistables :496, load_vars :546, save_inference_model :965,
load_inference_model :1157). The reference emits save/load OPS and runs a
save program; here the executor scope already holds jax arrays, so
checkpointing is a direct (sharding-aware) serialization of scope state plus
the serialized Program — the orbax-style pytree checkpoint in fluid clothing.
"""
from __future__ import annotations

import json
import os
import shutil
from typing import List, Optional

import numpy as np

from .executor import Scope, global_scope
from .framework import Parameter, Program, Variable

__all__ = ["save_vars", "save_params", "save_persistables", "load_vars",
           "load_params", "load_persistables", "save_inference_model",
           "load_inference_model", "save_checkpoint", "load_checkpoint"]

_MANIFEST = "manifest.json"


def _ensure_dir(dirname: str) -> None:
    """makedirs with a CLEAR diagnostic when the target exists as a file
    (the bare OSError from makedirs names neither the caller nor the fix)."""
    if os.path.exists(dirname) and not os.path.isdir(dirname):
        raise ValueError(
            f"save: dirname '{dirname}' already exists as a FILE — "
            f"checkpoints and inference models are directories; remove the "
            f"file or pick another path")
    os.makedirs(dirname, exist_ok=True)


def _vars_of(program: Program, predicate) -> List[Variable]:
    return [v for v in program.list_vars() if predicate(v)]


def _save_var_list(executor, dirname: str, vars_: List[Variable],
                   scope: Optional[Scope], filename: Optional[str]):
    scope = scope or global_scope()
    _ensure_dir(dirname)
    manifest = {}
    blobs = {}
    for v in vars_:
        val = scope.find_var(v.name)
        if val is None:
            raise RuntimeError(f"save: variable '{v.name}' has no value in scope")
        arr = np.asarray(val)
        blobs[v.name] = arr
        manifest[v.name] = {"shape": list(arr.shape), "dtype": str(arr.dtype)}
    if filename is None:
        for name, arr in blobs.items():
            np.save(os.path.join(dirname, name.replace("/", "__") + ".npy"),
                    arr, allow_pickle=False)
    else:
        # combined blob is an npz archive (plain tensor bytes, never pickled
        # objects — loading an untrusted checkpoint must not execute code).
        # Passed as a file object so np.savez keeps the exact filename.
        with open(os.path.join(dirname, filename), "wb") as f:
            np.savez(f, **{n.replace("/", "__"): a for n, a in blobs.items()})
    with open(os.path.join(dirname, _MANIFEST), "w") as f:
        json.dump({"vars": manifest, "filename": filename}, f)


def _load_var_list(executor, dirname: str, vars_: List[Variable],
                   scope: Optional[Scope], filename: Optional[str]):
    import jax.numpy as jnp

    scope = scope or global_scope()
    manifest_path = os.path.join(dirname, _MANIFEST)
    manifest = None
    if os.path.exists(manifest_path):
        with open(manifest_path) as f:
            manifest = json.load(f)
    blobs = {}
    if filename is not None or (manifest and manifest.get("filename")):
        fname = filename or manifest["filename"]
        with np.load(os.path.join(dirname, fname),
                     allow_pickle=False) as combined:
            wanted = {v.name.replace("/", "__"): v.name for v in vars_}
            for key, name in wanted.items():
                if key not in combined:
                    raise RuntimeError(
                        f"load: '{name}' missing from checkpoint")
                blobs[name] = combined[key]
    # two phases: read + validate EVERYTHING, then commit to the scope —
    # a shape mismatch on the Nth var must not leave vars 0..N-1 from the
    # checkpoint mixed with the scope's previous values (recovery walks
    # rely on a failed load leaving the scope untouched)
    staged = []
    for v in vars_:
        if blobs:
            arr = blobs[v.name]
        else:
            path = os.path.join(dirname, v.name.replace("/", "__") + ".npy")
            if not os.path.exists(path):
                raise RuntimeError(f"load: '{path}' not found")
            arr = np.load(path)
        if v.shape is not None and tuple(arr.shape) != tuple(v.shape) \
                and -1 not in (v.shape or ()):
            raise RuntimeError(
                f"load: shape mismatch for '{v.name}': checkpoint "
                f"{arr.shape} vs program {v.shape}")
        staged.append((v.name, arr))
    for name, arr in staged:
        scope.set_var(name, jnp.asarray(arr))


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None, scope=None):
    from .framework import default_main_program

    program = main_program or default_main_program()
    if vars is None:
        vars = _vars_of(program, predicate or (lambda v: v.persistable))
    _save_var_list(executor, dirname, vars, scope, filename)


def save_params(executor, dirname, main_program=None, filename=None,
                scope=None):
    save_vars(executor, dirname, main_program,
              predicate=lambda v: isinstance(v, Parameter), filename=filename,
              scope=scope)


def save_persistables(executor, dirname, main_program=None, filename=None,
                      scope=None):
    save_vars(executor, dirname, main_program,
              predicate=lambda v: v.persistable, filename=filename,
              scope=scope)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None, scope=None):
    from .framework import default_main_program

    program = main_program or default_main_program()
    if vars is None:
        vars = _vars_of(program, predicate or (lambda v: v.persistable))
    _load_var_list(executor, dirname, vars, scope, filename)


def load_params(executor, dirname, main_program=None, filename=None,
                scope=None):
    load_vars(executor, dirname, main_program,
              predicate=lambda v: isinstance(v, Parameter), filename=filename,
              scope=scope)


def load_persistables(executor, dirname, main_program=None, filename=None,
                      scope=None):
    load_vars(executor, dirname, main_program,
              predicate=lambda v: v.persistable, filename=filename,
              scope=scope)


# ---------------------------------------------------------------------------
# Inference model export (reference io.py:965 prunes to feed/fetch + saves)
# ---------------------------------------------------------------------------

def _prune_for_inference(program: Program, feed_names, fetch_names) -> Program:
    """Keep only ops on the path from feeds to fetches (reference Prune,
    framework/prune.cc)."""
    pruned = program.clone(for_test=True)
    block = pruned.global_block
    needed = set(fetch_names)
    keep = []
    for op in reversed(block.ops):
        if any(n in needed for n in op.output_arg_names):
            keep.append(op)
            needed.update(op.input_arg_names)
    block.ops = list(reversed(keep))
    used = set()
    for op in block.ops:
        used.update(op.input_arg_names)
        used.update(op.output_arg_names)
    used.update(feed_names)
    used.update(fetch_names)
    block.vars = {n: v for n, v in block.vars.items() if n in used}
    return pruned


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, scope=None):
    from .framework import default_main_program

    program = main_program or default_main_program()
    fetch_names = [t.name if isinstance(t, Variable) else t
                   for t in target_vars]
    pruned = _prune_for_inference(program, feeded_var_names, fetch_names)
    _ensure_dir(dirname)
    model_filename = model_filename or "__model__"
    with open(os.path.join(dirname, model_filename), "w") as f:
        json.dump({"program": pruned.to_dict(),
                   "feed_names": list(feeded_var_names),
                   "fetch_names": fetch_names}, f)
    params = [v for v in pruned.list_vars() if v.persistable]
    _save_var_list(executor, os.path.join(dirname, "params"), params, scope,
                   params_filename)
    return fetch_names


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None, scope=None):
    model_filename = model_filename or "__model__"
    with open(os.path.join(dirname, model_filename)) as f:
        meta = json.load(f)
    program = Program.from_dict(meta["program"])
    params = [v for v in program.list_vars() if v.persistable]
    _load_var_list(executor, os.path.join(dirname, "params"), params, scope,
                   params_filename)
    fetch_vars = [program.global_block.var(n) for n in meta["fetch_names"]]
    return program, meta["feed_names"], fetch_vars


# full-checkpoint helpers (beyond the reference CheckpointConfig save: adds
# step/meta AND crash-safety — see paddle_tpu.resilience.checkpoint and
# docs/RESILIENCE.md for the failure model and manifest schema)
def save_checkpoint(executor, dirname, main_program=None, scope=None,
                    meta: dict = None, mesh=None):
    """Crash-safe checkpoint write: everything lands in a temp sibling dir
    first (``.<name>.tmp.<pid>``), the manifest gains per-file sha256 +
    param inventory + framework version, files and directories are fsynced,
    and only then is the temp dir atomically renamed into place. A process
    killed at ANY point leaves either the complete previous checkpoint or
    the complete new one at ``dirname`` — never a torn mixture. The torn
    temp dir a kill leaves behind is ignored by recovery
    (``resilience.iter_serials``) and overwritten by the next save.

    ``mesh`` (a jax Mesh, ``{'dp': 8}`` or an int shard count) selects the
    SHARDED format (manifest format_version 2,
    ``resilience.distributed``): vars whose live sharding splits a dim
    over the dp axis are written one slice per fsynced shard file, so a
    ZeRO-sharded optimizer state never needs a full gather to checkpoint
    and a restore is elastic across device counts."""
    from .framework import default_main_program
    from .resilience import checkpoint as _rck
    from .resilience import distributed as _dist
    from .resilience.faults import fault_point

    dirname = os.path.normpath(dirname)
    if os.path.exists(dirname) and not os.path.isdir(dirname):
        raise ValueError(
            f"save_checkpoint: '{dirname}' already exists as a FILE — "
            f"checkpoints are directories")
    parent = os.path.dirname(os.path.abspath(dirname))
    os.makedirs(parent, exist_ok=True)
    tmp = os.path.join(parent, f".{os.path.basename(dirname)}.tmp."
                               f"{os.getpid()}")
    if os.path.exists(tmp):
        shutil.rmtree(tmp, ignore_errors=True)
    try:
        if mesh is not None:
            program = main_program or default_main_program()
            vars_ = [v for v in program.list_vars() if v.persistable]
            _ensure_dir(tmp)
            _dist.save_sharded_vars(tmp, vars_, scope or global_scope(),
                                    mesh)
        else:
            save_persistables(executor, tmp, main_program,
                              filename="ckpt.npz", scope=scope)
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta or {}, f)
            f.flush()
            os.fsync(f.fileno())
        # blobs are on disk, manifest/rename have not happened: a kill here
        # (FLAGS_fault_plan site) is the worst case the design must survive
        fault_point("ckpt_write")
        _rck.finalize_manifest(tmp)
        _rck.atomic_replace_dir(tmp, dirname)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


def load_checkpoint(executor, dirname, main_program=None, scope=None,
                    verify: bool = True) -> dict:
    """Verify the checkpoint's manifest (per-file sha256, format version)
    BEFORE loading a single byte, then restore persistables and return the
    meta dict. A torn or tampered checkpoint raises
    ``resilience.CheckpointCorruptError`` with a PT6xx code naming what
    failed — it never half-loads into the scope. ``verify=False`` skips
    integrity checks (for checkpoints written by pre-resilience builds).

    Sharded (format_version 2) checkpoints are reassembled to full values
    — the full-gather-equivalent restore — so a checkpoint saved on dp=8
    loads bit-identically on dp=4 or a single host; the next dispatch
    re-shards onto whatever mesh the resumed run has."""
    from .resilience import checkpoint as _rck

    manifest = None
    if verify:
        manifest = _rck.verify_checkpoint(dirname)
    else:
        mpath = os.path.join(dirname, _rck.MANIFEST_NAME)
        if os.path.exists(mpath):
            try:
                with open(mpath) as f:
                    manifest = json.load(f)
            except (ValueError, OSError):
                manifest = None
    if isinstance(manifest, dict) and manifest.get("sharding") is not None:
        from .framework import default_main_program
        from .resilience import distributed as _dist

        program = main_program or default_main_program()
        vars_ = [v for v in program.list_vars() if v.persistable]
        _dist.load_sharded_vars(dirname, manifest, vars_,
                                scope or global_scope())
    else:
        load_persistables(executor, dirname, main_program,
                          filename="ckpt.npz", scope=scope)
    meta_path = os.path.join(dirname, "meta.json")
    if not os.path.exists(meta_path):
        return {}
    with open(meta_path) as f:
        return json.load(f)


# reference fluid.io re-exports the data pipeline (python/paddle/fluid/io.py
# pulls DataLoader/PyReader from reader.py)
from .reader import DataLoader, PyReader  # noqa: E402,F401
