"""UCI housing loader (reference: python/paddle/dataset/uci_housing.py).

Real data: place ``housing.data`` under ``$DATA_HOME/uci_housing/``.
Otherwise synthesizes a linear-plus-noise regression with 13 features, so
fit_a_line converges exactly as the book test expects.
Sample tuple: (features float32[13], price float32[1]).
"""
from __future__ import annotations

import numpy as np

from .common import cached_path, synthetic_notice

__all__ = ["train", "test"]

_N_TRAIN, _N_TEST = 404, 102  # real split sizes

_TRUE_W = np.array([0.8, -1.2, 0.5, 2.0, -0.7, 1.5, 0.1, -0.4, 0.9, -1.1,
                    0.3, 0.6, -2.0], np.float32)


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)
    xs = rng.randn(n, 13).astype(np.float32)
    ys = xs @ _TRUE_W + 3.0 + rng.randn(n).astype(np.float32) * 0.1
    return xs, ys.reshape(-1, 1).astype(np.float32)


def _load_real(path):
    raw = np.loadtxt(path).astype(np.float32)
    feats, prices = raw[:, :-1], raw[:, -1:]
    # reference normalizes features to zero-mean unit-ish range
    feats = (feats - feats.mean(0)) / (feats.max(0) - feats.min(0) + 1e-8)
    return feats, prices


def _reader(split: str):
    path = cached_path("uci_housing", "housing.data")
    n = _N_TRAIN if split == "train" else _N_TEST
    seed = 0 if split == "train" else 1

    def reader():
        if path:
            feats, prices = _load_real(path)
            lo, hi = (0, _N_TRAIN) if split == "train" \
                else (_N_TRAIN, _N_TRAIN + _N_TEST)
            feats, prices = feats[lo:hi], prices[lo:hi]
        else:
            synthetic_notice("uci_housing")
            feats, prices = _synthetic(n, seed)
        for i in range(len(feats)):
            yield feats[i], prices[i]

    return reader


def train():
    return _reader("train")


def test():
    return _reader("test")
