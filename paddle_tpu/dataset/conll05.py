"""CoNLL-2005 SRL loader (reference: python/paddle/dataset/conll05.py).

Real data: place ``conll05st-tests.tar.gz`` extracts under
``$DATA_HOME/conll05/``. Otherwise synthesizes a learnable SRL-shaped task:
words near the predicate get argument tags by a fixed positional+lexical
rule (word class + distance to predicate decide the IOB tag), so an
embedding + LSTM + CRF pipeline genuinely learns structure.

Sample tuple (simplified from the reference's 9-slot sample; the book model
consumes these): (word_ids int64[T], predicate_id int64, mark int64[T]
— 1 at predicate positions, label_ids int64[T] IOB over
``num_chunk_types`` argument types + O).
"""
from __future__ import annotations

import numpy as np

from .common import synthetic_notice

__all__ = ["train", "test", "get_dict", "get_embedding", "word_dict_len",
           "label_dict_len", "predicate_dict_len", "num_chunk_types"]

_VOCAB, _N_PRED, _N_TYPES = 800, 64, 3
_MIN_LEN, _MAX_LEN = 5, 12
_N_TRAIN, _N_TEST = 16384, 512


def word_dict_len():
    return _VOCAB


def predicate_dict_len():
    return _N_PRED


def num_chunk_types():
    return _N_TYPES


def label_dict_len():
    # IOB: B/I per type + O
    return 2 * _N_TYPES + 1


def get_dict():
    wd = {f"w{i}": i for i in range(_VOCAB)}
    vd = {f"v{i}": i for i in range(_N_PRED)}
    ld = {f"l{i}": i for i in range(label_dict_len())}
    return wd, vd, ld


def get_embedding():
    rng = np.random.RandomState(5)
    return rng.randn(_VOCAB, 32).astype(np.float32)


def _label_rule(words, pred_pos):
    """B-type at the word RIGHT BEFORE/AFTER the predicate when the word's
    class (word_id mod (types+1)) is a type; I-type continues while the
    class repeats; O elsewhere. Deterministic + position-sensitive."""
    t = len(words)
    labels = np.full(t, 2 * _N_TYPES, np.int64)           # O
    for pos in (pred_pos - 1, pred_pos + 1):
        if 0 <= pos < t:
            cls = int(words[pos]) % (_N_TYPES + 1)
            if cls < _N_TYPES:
                labels[pos] = 2 * cls                      # B-cls
                q = pos + 1
                while q < t and int(words[q]) % (_N_TYPES + 1) == cls \
                        and q != pred_pos:
                    labels[q] = 2 * cls + 1                # I-cls
                    q += 1
    return labels


def _reader(n, seed):
    def read():
        synthetic_notice("conll05")
        rng = np.random.RandomState(seed)
        for _ in range(n):
            t = int(rng.randint(_MIN_LEN, _MAX_LEN + 1))
            words = rng.randint(0, _VOCAB, t).astype(np.int64)
            pred_pos = int(rng.randint(0, t))
            predicate = np.int64(int(words[pred_pos]) % _N_PRED)
            mark = np.zeros(t, np.int64)
            mark[pred_pos] = 1
            labels = _label_rule(words, pred_pos)
            yield words, predicate, mark, labels
    return read


def train():
    return _reader(_N_TRAIN, 0)


def test():
    return _reader(_N_TEST, 1)
