"""IMDB sentiment loader (reference: python/paddle/dataset/imdb.py).

Real data: place ``aclImdb_v1.tar.gz`` under ``$DATA_HOME/imdb/`` — the word
dict is then built from the real train corpus by frequency (the reference's
``build_dict(pattern, cutoff=150)``). Otherwise synthesizes a sentiment task
with a planted signal: a vocab where word ids below ``_POS_BAND`` lean
positive and ids above lean negative; documents are sampled from the matching
band, so bag-of-words / embedding models genuinely learn. Sample tuple:
(word-id list int64 varlen, label int64 {0,1}).
"""
from __future__ import annotations

import re
import tarfile

import numpy as np

from .common import cached_path, synthetic_notice

__all__ = ["word_dict", "train", "test"]

_VOCAB = 5149  # mimics the reference's cutoff-150 dict size scale
_N_TRAIN, _N_TEST = 2048, 256
_MIN_LEN, _MAX_LEN = 8, 120
_CUTOFF = 150  # reference imdb.py word_dict cutoff
_real_dict = None


def _tokenize(raw: bytes):
    return raw.decode("utf-8", "ignore").lower().split()


def word_dict():
    """reference imdb.word_dict(): word -> id, built by corpus frequency when
    the real archive is present; synthetic fallback maps 'w<i>' -> i."""
    global _real_dict
    path = cached_path("imdb", "aclImdb_v1.tar.gz")
    if not path:
        return {f"w{i}": i for i in range(_VOCAB)}
    if _real_dict is None:
        freq: dict = {}
        pat = re.compile(r"aclImdb/train/(pos|neg)/.*\.txt$")
        with tarfile.open(path, "r:gz") as tar:
            for member in tar.getmembers():
                if not pat.match(member.name):
                    continue
                for w in _tokenize(tar.extractfile(member).read()):
                    freq[w] = freq.get(w, 0) + 1
        # frequency-descending, ties by word, as the reference sorts
        kept = sorted((w for w, c in freq.items() if c >= _CUTOFF),
                      key=lambda w: (-freq[w], w))
        _real_dict = {w: i for i, w in enumerate(kept)}
        # reference appends '<unk>' = len(words) so unknown ids stay in range
        _real_dict["<unk>"] = len(_real_dict)
    return _real_dict


def _reader(split: str, wd=None):
    path = cached_path("imdb", "aclImdb_v1.tar.gz")
    n = _N_TRAIN if split == "train" else _N_TEST
    seed = 0 if split == "train" else 1

    def reader():
        if path:
            d = wd if wd is not None else word_dict()
            unk = d.get("<unk>", len(d) - 1)
            pat = re.compile(rf"aclImdb/{split}/(pos|neg)/.*\.txt$")
            with tarfile.open(path, "r:gz") as tar:
                for member in tar.getmembers():
                    m = pat.match(member.name)
                    if not m:
                        continue
                    ids = [d.get(w, unk)
                           for w in _tokenize(tar.extractfile(member).read())]
                    yield ids, int(m.group(1) == "pos")
        else:
            synthetic_notice("imdb")
            yield from _synthetic(n, seed)

    return reader


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)
    docs = []
    for _ in range(n):
        label = int(rng.randint(0, 2))
        length = int(rng.randint(_MIN_LEN, _MAX_LEN + 1))
        # positive docs draw 70% of words from the low band, negative from
        # the high band; 30% uniform noise
        band = rng.rand(length) < 0.7
        half = _VOCAB // 2
        lo = rng.randint(0, half, length)
        hi = rng.randint(half, _VOCAB, length)
        signal = lo if label == 1 else hi
        noise = rng.randint(0, _VOCAB, length)
        words = np.where(band, signal, noise).astype(np.int64)
        docs.append((list(words), label))
    return docs


def train(word_dict=None):
    return _reader("train", word_dict)


def test(word_dict=None):
    return _reader("test", word_dict)
