"""Shared dataset plumbing (reference: python/paddle/dataset/common.py).

``download`` is gated: with no network egress it raises unless the file is
already cached, and every loader catches that and synthesizes data instead.
"""
from __future__ import annotations

import os
import sys

__all__ = ["DATA_HOME", "cached_path", "synthetic_notice"]

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


def cached_path(module: str, filename: str):
    """Path of a real data file if the user has placed it in the cache
    (reference download() target layout); None otherwise."""
    p = os.path.join(DATA_HOME, module, filename)
    return p if os.path.exists(p) else None


_warned = set()


def synthetic_notice(name: str):
    if name not in _warned:
        _warned.add(name)
        print(f"[paddle_tpu.dataset] '{name}' not found under {DATA_HOME}; "
              f"using deterministic synthetic data (no network egress)",
              file=sys.stderr)
