"""Flowers-102 loader (reference: python/paddle/dataset/flowers.py).

Real data: place ``102flowers.tgz`` + ``imagelabels.mat`` + ``setid.mat``
under ``$DATA_HOME/flowers/``. Otherwise synthesizes class-structured
images: each of the 102 classes carries a fixed color/texture template.
Sample tuple: (image float32[3*224*224] in [0, 1], label int64 in [0, 102)).
"""
from __future__ import annotations

import numpy as np

from .common import synthetic_notice

__all__ = ["train", "test", "valid"]

_N_CLASSES = 102
_DIM = 3 * 224 * 224
_N_TRAIN, _N_TEST, _N_VALID = 2048, 256, 256


def _templates():
    rng = np.random.RandomState(777)
    # low-res template upsampled: keeps the synthetic file small in memory
    small = rng.rand(_N_CLASSES, 3, 16, 16).astype(np.float32)
    return small


def _reader(n, seed):
    def read():
        synthetic_notice("flowers")
        tmpl = _templates()
        rng = np.random.RandomState(seed)
        for _ in range(n):
            lb = int(rng.randint(0, _N_CLASSES))
            img = np.kron(tmpl[lb], np.ones((1, 14, 14), np.float32))
            img = np.clip(img * 0.7 + 0.3 * rng.rand(3, 224, 224), 0, 1)
            yield img.reshape(-1).astype(np.float32), np.int64(lb)
    return read


def train(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _reader(_N_TRAIN, 0)


def test(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _reader(_N_TEST, 1)


def valid(mapper=None, buffered_size=1024, use_xmap=True):
    return _reader(_N_VALID, 2)
