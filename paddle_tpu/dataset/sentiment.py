"""NLTK movie-review sentiment loader (reference:
python/paddle/dataset/sentiment.py).

Real data: place the ``movie_reviews`` corpus under
``$DATA_HOME/sentiment/``. Otherwise synthesizes polarity-bearing word
sequences: positive/negative vocab halves with mixing noise, so a
bag-of-words classifier genuinely learns.
Sample tuple: (word_ids int64[T] (T varies 8..40), label int64 {0, 1}).
"""
from __future__ import annotations

import numpy as np

from .common import synthetic_notice

__all__ = ["train", "test", "get_word_dict"]

_VOCAB = 5000
_N_TRAIN, _N_TEST = 4096, 512


def get_word_dict():
    return {f"w{i}": i for i in range(_VOCAB)}


def _reader(n, seed):
    def read():
        synthetic_notice("sentiment")
        rng = np.random.RandomState(seed)
        half = _VOCAB // 2
        for _ in range(n):
            label = int(rng.randint(0, 2))
            t = int(rng.randint(8, 41))
            polar = rng.randint(label * half, label * half + half, t)
            noise = rng.randint(0, _VOCAB, t)
            keep = rng.rand(t) < 0.7
            words = np.where(keep, polar, noise)
            yield words.astype(np.int64), np.int64(label)
    return read


def train():
    return _reader(_N_TRAIN, 0)


def test():
    return _reader(_N_TEST, 1)
