"""PASCAL VOC2012 segmentation loader (reference:
python/paddle/dataset/voc2012.py).

Real data: place ``VOCtrainval_11-May-2012.tar`` extracts under
``$DATA_HOME/voc2012/``. Otherwise synthesizes images whose segmentation
mask is recoverable from color (each of the 21 classes paints its region
with a class-correlated color), so a small FCN genuinely learns.
Sample tuple: (image float32[3, 64, 64] in [0, 1],
label int64[64, 64] in [0, 21)).
"""
from __future__ import annotations

import numpy as np

from .common import synthetic_notice

__all__ = ["train", "test", "val"]

_N_CLASSES, _HW = 21, 64
_N_TRAIN, _N_TEST = 1024, 128


def _reader(n, seed):
    def read():
        synthetic_notice("voc2012")
        crng = np.random.RandomState(55)
        colors = crng.rand(_N_CLASSES, 3).astype(np.float32)
        rng = np.random.RandomState(seed)
        for _ in range(n):
            mask = np.zeros((_HW, _HW), np.int64)
            for _blob in range(int(rng.randint(1, 4))):
                c = int(rng.randint(1, _N_CLASSES))
                y0, x0 = rng.randint(0, _HW - 16, 2)
                h, w = rng.randint(8, 17, 2)
                mask[y0:y0 + h, x0:x0 + w] = c
            img = colors[mask].transpose(2, 0, 1)
            img = np.clip(img + 0.15 * rng.randn(3, _HW, _HW), 0, 1)
            yield img.astype(np.float32), mask
    return read


def train():
    return _reader(_N_TRAIN, 0)


def test():
    return _reader(_N_TEST, 1)


def val():
    return _reader(_N_TEST, 2)
