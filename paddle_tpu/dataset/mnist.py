"""MNIST loader (reference: python/paddle/dataset/mnist.py:1).

Real data: place ``train-images-idx3-ubyte.gz`` etc. under
``$DATA_HOME/mnist/``. Otherwise synthesizes class-structured digits: each
class k has a fixed template blob; samples are the template + noise, so a
small MLP genuinely learns (accuracy >> chance), unlike pure-noise data.
Sample tuple: (image float32[784] in [-1, 1], label int64).
"""
from __future__ import annotations

import gzip
import struct

import numpy as np

from .common import cached_path, synthetic_notice

__all__ = ["train", "test"]

_N_TRAIN, _N_TEST = 8192, 1024


def _templates():
    rng = np.random.RandomState(1234)
    return rng.rand(10, 784).astype(np.float32) * 2 - 1


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)
    tmpl = _templates()
    labels = rng.randint(0, 10, n)
    imgs = tmpl[labels] * 0.6 + rng.randn(n, 784).astype(np.float32) * 0.35
    return np.clip(imgs, -1, 1).astype(np.float32), labels.astype(np.int64)


def _read_idx(img_path, lbl_path):
    with gzip.open(img_path, "rb") as f:
        _, n, rows, cols = struct.unpack(">IIII", f.read(16))
        imgs = np.frombuffer(f.read(), np.uint8).reshape(n, rows * cols)
    with gzip.open(lbl_path, "rb") as f:
        _, n = struct.unpack(">II", f.read(8))
        labels = np.frombuffer(f.read(), np.uint8)
    imgs = imgs.astype(np.float32) / 255.0 * 2.0 - 1.0
    return imgs, labels.astype(np.int64)


def _reader(split: str):
    if split == "train":
        files = ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz")
        n, seed = _N_TRAIN, 0
    else:
        files = ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz")
        n, seed = _N_TEST, 1
    img_p, lbl_p = cached_path("mnist", files[0]), cached_path("mnist",
                                                               files[1])

    def reader():
        if img_p and lbl_p:
            imgs, labels = _read_idx(img_p, lbl_p)
        else:
            synthetic_notice("mnist")
            imgs, labels = _synthetic(n, seed)
        for i in range(len(labels)):
            yield imgs[i], int(labels[i])

    return reader


def train():
    return _reader("train")


def test():
    return _reader("test")
