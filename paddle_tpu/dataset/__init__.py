"""Built-in dataset loaders (reference: python/paddle/dataset/).

The reference downloads real archives from paddlepaddle.org
(dataset/common.py download()). This build environment has NO network
egress, so each loader first looks for the real files in
``~/.cache/paddle_tpu/dataset`` (drop them there to train on real data) and
otherwise falls back to a deterministic synthetic sample with the exact
shapes/dtypes/value-ranges of the real dataset — enough to drive every
pipeline, model and test. The reader contract is the reference one: a
loader returns a zero-arg creator whose iterator yields sample tuples.
"""
from . import (cifar, conll05, flowers, imdb, imikolov,  # noqa: F401
               mnist, movielens, sentiment, uci_housing, voc2012,
               wmt14, wmt16)

__all__ = ["mnist", "cifar", "imdb", "imikolov", "uci_housing",
           "movielens", "conll05", "wmt16", "wmt14", "flowers",
           "sentiment", "voc2012"]
