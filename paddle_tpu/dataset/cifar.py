"""CIFAR-10/100 loader (reference: python/paddle/dataset/cifar.py).

Real data: place ``cifar-10-python.tar.gz`` / ``cifar-100-python.tar.gz``
under ``$DATA_HOME/cifar/``. Otherwise synthesizes class-structured images
(per-class color/template signature + noise).
Sample tuple: (image float32[3072] in [0, 1], label int64).
"""
from __future__ import annotations

import pickle
import tarfile

import numpy as np

from .common import cached_path, synthetic_notice

__all__ = ["train10", "test10", "train100", "test100"]

_N_TRAIN, _N_TEST = 4096, 512


def _synthetic(n, n_classes, seed):
    rng = np.random.RandomState(4321 + n_classes)
    tmpl = rng.rand(n_classes, 3072).astype(np.float32)
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, n_classes, n)
    imgs = tmpl[labels] * 0.5 + rng.rand(n, 3072).astype(np.float32) * 0.5
    return imgs.astype(np.float32), labels.astype(np.int64)


def _tar_reader(path, names, label_key):
    with tarfile.open(path, "r:gz") as tar:
        for member in tar.getmembers():
            if any(member.name.endswith(n) for n in names):
                batch = pickle.loads(tar.extractfile(member).read(),
                                     encoding="bytes")
                data = batch[b"data"].astype(np.float32) / 255.0
                labels = batch[label_key]
                for img, lbl in zip(data, labels):
                    yield img, int(lbl)


def _reader(n_classes: int, split: str):
    if n_classes == 10:
        fname, label_key = "cifar-10-python.tar.gz", b"labels"
        names = [f"data_batch_{i}" for i in range(1, 6)] \
            if split == "train" else ["test_batch"]
    else:
        fname, label_key = "cifar-100-python.tar.gz", b"fine_labels"
        names = ["train"] if split == "train" else ["test"]
    path = cached_path("cifar", fname)
    n = _N_TRAIN if split == "train" else _N_TEST
    seed = 0 if split == "train" else 1

    def reader():
        if path:
            yield from _tar_reader(path, names, label_key)
        else:
            synthetic_notice(f"cifar{n_classes}")
            imgs, labels = _synthetic(n, n_classes, seed)
            for i in range(n):
                yield imgs[i], int(labels[i])

    return reader


def train10():
    return _reader(10, "train")


def test10():
    return _reader(10, "test")


def train100():
    return _reader(100, "train")


def test100():
    return _reader(100, "test")
