"""MovieLens-1M loader (reference: python/paddle/dataset/movielens.py).

Real data: place ``ml-1m.zip``'s extracted ``ratings.dat``/``users.dat``/
``movies.dat`` under ``$DATA_HOME/movielens/``. Otherwise synthesizes a
low-rank user x movie preference structure: each user and movie carries a
latent factor and the rating is their (noised, quantized) inner product —
so a factorization-style recommender genuinely learns.

Sample tuple (reference movielens.py __initialize_meta_info__ ordering):
(user_id, gender_id, age_id, job_id, movie_id, category_ids [var-len],
 title_ids [var-len], score float32).
"""
from __future__ import annotations

import numpy as np

from .common import synthetic_notice

__all__ = ["train", "test", "user_info", "movie_info", "max_user_id",
           "max_movie_id", "max_job_id", "age_table", "categories_dict_size",
           "title_dict_size"]

_N_USERS, _N_MOVIES, _RANK = 512, 256, 6
_N_CATEGORIES, _TITLE_VOCAB, _TITLE_LEN = 18, 1024, 4
_N_TRAIN, _N_TEST = 16384, 2048

age_table = [1, 18, 25, 35, 45, 50, 56]


def max_user_id():
    return _N_USERS


def max_movie_id():
    return _N_MOVIES


def max_job_id():
    return 20


def categories_dict_size():
    return _N_CATEGORIES


def title_dict_size():
    return _TITLE_VOCAB


def _factors():
    rng = np.random.RandomState(2024)
    u = rng.randn(_N_USERS + 1, _RANK).astype(np.float32)
    m = rng.randn(_N_MOVIES + 1, _RANK).astype(np.float32)
    meta = {
        "gender": rng.randint(0, 2, _N_USERS + 1),
        "age": rng.randint(0, len(age_table), _N_USERS + 1),
        "job": rng.randint(0, max_job_id() + 1, _N_USERS + 1),
        "cats": rng.randint(0, _N_CATEGORIES, (_N_MOVIES + 1, 2)),
        "titles": rng.randint(0, _TITLE_VOCAB, (_N_MOVIES + 1, _TITLE_LEN)),
    }
    return u, m, meta


def user_info():
    """Per-user metadata (reference movielens.py user_info contract)."""
    _, _, meta = _factors()
    return {"gender": meta["gender"], "age": meta["age"],
            "job": meta["job"]}


def movie_info():
    """Per-movie metadata (reference movielens.py movie_info contract)."""
    _, _, meta = _factors()
    return {"categories": meta["cats"], "title_ids": meta["titles"]}


def _reader(n, seed):
    def read():
        synthetic_notice("movielens")
        u, m, meta = _factors()
        rng = np.random.RandomState(seed)
        for _ in range(n):
            uid = int(rng.randint(1, _N_USERS + 1))
            mid = int(rng.randint(1, _N_MOVIES + 1))
            raw = float(u[uid] @ m[mid]) / np.sqrt(_RANK)
            score = float(np.clip(np.round(3.0 + 1.5 * raw
                                           + 0.3 * rng.randn()), 1, 5))
            yield (np.int64(uid), np.int64(meta["gender"][uid]),
                   np.int64(meta["age"][uid]), np.int64(meta["job"][uid]),
                   np.int64(mid), meta["cats"][mid].astype(np.int64),
                   meta["titles"][mid].astype(np.int64),
                   np.float32(score))
    return read


def train():
    return _reader(_N_TRAIN, 0)


def test():
    return _reader(_N_TEST, 1)
