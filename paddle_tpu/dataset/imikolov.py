"""PTB/imikolov n-gram loader (reference: python/paddle/dataset/imikolov.py).

Real data: place ``simple-examples.tgz`` under ``$DATA_HOME/imikolov/``.
Otherwise synthesizes a corpus from a planted first-order Markov chain, so
an n-gram next-word model (the book word2vec test) genuinely learns.
``train(word_dict, n)`` yields n-tuples of word ids (the n-1 context words
plus the target), exactly the reference contract.
"""
from __future__ import annotations

import tarfile

import numpy as np

from .common import cached_path, synthetic_notice

__all__ = ["build_dict", "train", "test"]

_VOCAB = 200
_N_TRAIN_SENT, _N_TEST_SENT = 512, 64


def build_dict(min_word_freq: int = 50):
    path = cached_path("imikolov", "simple-examples.tgz")
    if not path:
        return {f"w{i}": i for i in range(_VOCAB)}
    freq: dict = {}
    with tarfile.open(path, "r:gz") as tar:
        f = tar.extractfile("./simple-examples/data/ptb.train.txt")
        for line in f.read().decode("utf-8").splitlines():
            for w in line.strip().split():
                freq[w] = freq.get(w, 0) + 1
    kept = sorted((w for w, c in freq.items() if c >= min_word_freq),
                  key=lambda w: (-freq[w], w))
    d = {w: i for i, w in enumerate(kept)}
    d["<unk>"] = len(d)
    return d


def _synthetic_sentences(n, seed, vocab=_VOCAB):
    """First-order Markov chain: word w transitions to one of 4 preferred
    successors with prob 0.8 — n-gram models reach low perplexity on it."""
    rng = np.random.RandomState(seed)
    succ = rng.randint(0, vocab, (vocab, 4))
    sents = []
    for _ in range(n):
        length = int(rng.randint(8, 20))
        w = int(rng.randint(0, vocab))
        sent = [w]
        for _ in range(length - 1):
            if rng.rand() < 0.8:
                w = int(succ[w, rng.randint(0, 4)])
            else:
                w = int(rng.randint(0, vocab))
            sent.append(w)
        sents.append(sent)
    return sents


def _reader(split: str, word_dict, n: int):
    path = cached_path("imikolov", "simple-examples.tgz")
    count = _N_TRAIN_SENT if split == "train" else _N_TEST_SENT
    seed = 0 if split == "train" else 1

    def reader():
        if path:
            name = f"./simple-examples/data/ptb.{split}.txt" \
                if split != "test" else "./simple-examples/data/ptb.valid.txt"
            unk = word_dict.get("<unk>", len(word_dict) - 1)
            with tarfile.open(path, "r:gz") as tar:
                f = tar.extractfile(name)
                for line in f.read().decode("utf-8").splitlines():
                    ids = [word_dict.get(w, unk)
                           for w in line.strip().split()]
                    for i in range(len(ids) - n + 1):
                        yield tuple(ids[i:i + n])
        else:
            synthetic_notice("imikolov")
            # respect a caller-supplied (possibly smaller) dict: ids must
            # stay in range of the embedding it sizes
            vocab = min(_VOCAB, len(word_dict)) if word_dict else _VOCAB
            for sent in _synthetic_sentences(count, seed, vocab):
                for i in range(len(sent) - n + 1):
                    yield tuple(sent[i:i + n])

    return reader


def train(word_dict, n: int = 5):
    return _reader("train", word_dict, n)


def test(word_dict, n: int = 5):
    return _reader("test", word_dict, n)
