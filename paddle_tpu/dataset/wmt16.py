"""WMT16 translation loader (reference: python/paddle/dataset/wmt16.py).

Real data: place ``wmt16.tar.gz`` extracts under ``$DATA_HOME/wmt16/``.
Otherwise synthesizes a learnable toy translation shaped for
ATTENTION-FREE encoder-decoders (book/test_rnn_encoder_decoder.py): the
target is a Markov chain seeded by the source's first word — trg[0] =
m(src[0]), trg[i] = m(trg[i-1]) — so teacher-forced prediction is
deterministic given the previous target token (plus the encoder summary
for the first step) and perplexity genuinely collapses.

Sample tuple (reference wmt16 reader contract):
(src_ids int64[S], trg_ids int64[T] starting with BOS,
 trg_next_ids int64[T] ending with EOS — trg shifted by one).
"""
from __future__ import annotations

import numpy as np

from .common import synthetic_notice

__all__ = ["train", "test", "get_dict"]

_VOCAB = 130          # includes specials
BOS, EOS, UNK = 0, 1, 2
_MIN_LEN, _MAX_LEN = 3, 8
_N_TRAIN, _N_TEST = 8192, 512


def get_dict(lang="en", dict_size=_VOCAB, reverse=False):
    d = {f"{lang}_{i}": i for i in range(dict_size)}
    return {v: k for k, v in d.items()} if reverse else d


def _mapping():
    rng = np.random.RandomState(99)
    m = rng.permutation(_VOCAB - 3) + 3      # specials map to themselves
    return m


def _reader(n, seed):
    def read():
        synthetic_notice("wmt16")
        m = _mapping()
        rng = np.random.RandomState(seed)
        for _ in range(n):
            s = int(rng.randint(_MIN_LEN, _MAX_LEN + 1))
            src = rng.randint(3, _VOCAB, s).astype(np.int64)
            trg_full = np.empty(s, np.int64)
            cur = int(src[0])
            for i in range(s):
                cur = int(m[cur - 3])
                trg_full[i] = cur
            trg = np.concatenate([[BOS], trg_full])
            trg_next = np.concatenate([trg_full, [EOS]])
            yield src, trg, trg_next
    return read


def train(src_dict_size=_VOCAB, trg_dict_size=_VOCAB, src_lang="en"):
    return _reader(_N_TRAIN, 0)


def test(src_dict_size=_VOCAB, trg_dict_size=_VOCAB, src_lang="en"):
    return _reader(_N_TEST, 1)
