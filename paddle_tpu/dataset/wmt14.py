"""WMT14 en-fr loader (reference: python/paddle/dataset/wmt14.py).

Real data: place ``wmt14.tgz`` extracts under ``$DATA_HOME/wmt14/``.
Otherwise the same Markov-chain synthetic translation task as wmt16
(dataset/wmt16.py docstring), re-framed through the wmt14 API: samples are
(src_ids, trg_ids, trg_next_ids) with dict_size-bounded ids.
"""
from __future__ import annotations

from . import wmt16 as _w16

__all__ = ["train", "test", "get_dict"]


def train(dict_size=30000):
    return _w16.train()


def test(dict_size=30000):
    return _w16.test()


def get_dict(dict_size=30000, reverse=False):
    src = _w16.get_dict("en", reverse=reverse)
    trg = _w16.get_dict("fr", reverse=reverse)
    return src, trg
