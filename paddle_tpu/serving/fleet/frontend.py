"""Fleet front-end: the stdlib HTTP/JSON server over one serving engine.

One ``ServingFrontend`` wraps one :class:`~paddle_tpu.serving.ServingEngine`
(or :class:`GenerativeEngine`) and exposes it on the network — the layer
the ROADMAP's "millions of users" shape needs above the in-process engine
(reference: the gRPC/BRPC distributed runtime + serving fleet of
``paddle/fluid/operators/distributed/``). Stdlib only
(``http.server.ThreadingHTTPServer``): no new dependencies, one handler
thread per connection, the engine's own dispatch thread does the real
work.

Routes (wire schema: ``fleet.wire``, docs/SERVING.md "Fleet tier"):

* ``POST /v1/submit``   — request/response inference. Body carries the
  encoded feed, priority / SLO class and deadline; the response is the
  request's rows or a typed error with a DISTINCT status per outcome.
* ``POST /v1/generate`` — token streaming for a ``GenerativeEngine``:
  newline-delimited JSON chunks, one ``{"tokens": [...]}`` per emitted
  token, closed by a terminal ``{"done": true, ...}`` chunk carrying the
  typed outcome — a stream whose replica fails mid-generation delivers
  its partial tokens AND a typed terminal error, exactly like the
  in-process ``ServingFuture.stream()`` contract.
* ``GET /healthz``      — the engine's frozen ``health()`` payload
  (schema-versioned wire contract) plus replica identity and startup
  info (time-to-ready, warm-start cache stats).
* ``GET /readyz``       — 200/503 on ``ready()`` — the router's and any
  load balancer's routing signal; a draining replica flips 503 here
  while ``/healthz`` keeps answering.
* ``GET /metrics``      — the replica's monitor registry, Prometheus
  text exposition format 0.0.4; ``/metrics.json`` (or ``?format=json``)
  is the schema-versioned JSON form (``telemetry.metrics_json``) that
  additionally carries histogram trace exemplars, the SLO burn state
  and the per-tenant ledger. A probe route like ``/healthz``: the
  ``wire_response`` fault sites never fire here, so the telemetry plane
  stays observable while the request plane is under chaos.

Trace propagation: the ``X-PT-Trace`` request header carries the
caller's ``SpanContext`` across the wire; the front-end opens a
``fleet.request`` span under it and submits with ``trace_parent=`` so
the engine's request root — and every typed outcome and flight-recorder
incident — shares the caller's trace id across processes.

Metrics (docs/OBSERVABILITY.md): ``fleet_requests_total{route,outcome}``,
``fleet_request_seconds{route}``, ``fleet_stream_tokens_total``.
"""
from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from ... import monitor as _monitor
from ... import trace as _trace
from ...resilience import faults as _faults
from ...resilience.deadline import DeadlineExceeded
from ..engine import ServingError
from ..generate import GenerativeEngine
from . import wire

__all__ = ["ServingFrontend", "FrontendConfig"]

logger = logging.getLogger("paddle_tpu.serving.fleet")


class FrontendConfig:
    """Front-end knobs (plain defaults; the engine's own admission
    control is the load-shedding layer — the front-end only bounds how
    long a handler thread waits on a settled outcome)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 request_timeout_s: float = 120.0):
        self.host = host
        self.port = int(port)
        self.request_timeout_s = float(request_timeout_s)


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    frontend: "ServingFrontend" = None  # set by ServingFrontend.start

    def handle_error(self, request, client_address):
        # a client dropping its keep-alive connection is normal churn,
        # not a stack trace on stderr; real handler errors still answer
        # structured 500s in the handler itself
        import sys

        exc = sys.exc_info()[1]
        if isinstance(exc, (BrokenPipeError, ConnectionResetError,
                            TimeoutError)):
            logger.debug("fleet frontend: client %s dropped (%s)",
                         client_address, type(exc).__name__)
            return
        super().handle_error(request, client_address)


class ServingFrontend:
    """See module docstring. ``extra_health`` is merged into the
    ``/healthz`` body next to the engine payload (the replica worker
    reports startup timing + warm-start cache stats through it)."""

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0,
                 replica_id: str = "",
                 request_timeout_s: float = 120.0,
                 extra_health: Optional[Dict[str, Any]] = None):
        self.engine = engine
        self.config = FrontendConfig(host, port, request_timeout_s)
        self.replica_id = replica_id
        self.extra_health = dict(extra_health or {})
        self._server: Optional[_Server] = None
        self._thread: Optional[threading.Thread] = None
        self._inflight = 0
        self._inflight_lock = _monitor.make_lock(
            "ServingFrontend._inflight_lock")

    # -- lifecycle -------------------------------------------------------
    def start(self) -> int:
        """Bind + serve on a daemon thread. Returns the bound port
        (``port=0`` picks a free one)."""
        if self._server is not None:
            return self.port
        srv = _Server((self.config.host, self.config.port), _Handler)
        srv.frontend = self
        self._server = srv
        self._thread = threading.Thread(
            target=srv.serve_forever,
            name=f"paddle_tpu-fleet-frontend-{self.replica_id or 'r'}",
            daemon=True)
        self._thread.start()
        logger.info("fleet frontend %s serving on %s:%d",
                    self.replica_id or "(unnamed)", self.host, self.port)
        return self.port

    def stop(self, wait_inflight_s: float = 10.0) -> None:
        """Stop accepting connections; give in-flight handlers (e.g.
        responses for requests a draining engine just settled) a bounded
        window to finish writing."""
        srv, self._server = self._server, None
        if srv is None:
            return
        deadline = time.monotonic() + wait_inflight_s
        while time.monotonic() < deadline:
            with self._inflight_lock:
                if self._inflight == 0:
                    break
            time.sleep(0.02)
        srv.shutdown()
        srv.server_close()
        if self._thread is not None:
            self._thread.join(5.0)

    @property
    def host(self) -> str:
        return (self._server.server_address[0] if self._server
                else self.config.host)

    @property
    def port(self) -> int:
        return (self._server.server_address[1] if self._server
                else self.config.port)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def __enter__(self) -> "ServingFrontend":
        self.start()
        return self

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- health bodies ---------------------------------------------------
    def health_body(self) -> dict:
        body = self.engine.health()          # the frozen wire contract
        body["replica_id"] = self.replica_id
        # capability flag for the router's mixed-fleet dispatch: only a
        # GenerativeEngine replica serves /v1/generate (and its feed-less
        # engine serves no /v1/submit)
        body["generative"] = isinstance(self.engine, GenerativeEngine)
        if self.extra_health:
            body["startup"] = self.extra_health
        return body

    # -- metrics ---------------------------------------------------------
    @staticmethod
    def _count(route: str, outcome: str) -> None:
        if _monitor.enabled():
            _monitor.counter(
                "fleet_requests_total",
                "front-end HTTP requests by route and typed outcome"
            ).labels(route=route, outcome=outcome).inc()

    @staticmethod
    def _observe_latency(seconds: float, route: str,
                         trace_id: str = "") -> None:
        if _monitor.enabled():
            # exemplar: the request's trace id rides the bucket this
            # observation lands in (telemetry plane only — no exemplar
            # storage is ever allocated while the plane is off)
            ex = trace_id if _monitor.telemetry_enabled() else ""
            _monitor.histogram(
                "fleet_request_seconds",
                "front-end request wall time by route, admission to "
                "response written (p50/p99 in the snapshot)").labels(
                route=route).observe(seconds, exemplar=ex or None)


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    # -- plumbing --------------------------------------------------------
    @property
    def fe(self) -> ServingFrontend:
        return self.server.frontend

    def log_message(self, fmt, *args):   # stdlib default spams stderr
        logger.debug("fleet http %s", fmt % args)

    def _body(self) -> bytes:
        n = int(self.headers.get("Content-Length", 0) or 0)
        return self.rfile.read(n) if n else b""

    def _send_json(self, status: int, obj: dict,
                   corrupt: bool = False) -> None:
        raw = wire.dumps(obj)
        if corrupt and raw:
            # the wire_response 'corrupt' action: same length, mangled
            # bytes — the router must classify this typed, never return
            # a silent empty result
            raw = b"\xff" + raw[1:]
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def _trace_parent(self):
        return _trace.SpanContext.from_wire(
            self.headers.get(wire.TRACE_HEADER))

    # -- routes ----------------------------------------------------------
    def do_GET(self):
        with self._track():
            path, _, query = self.path.partition("?")
            if path == "/healthz":
                self._send_json(200, self.fe.health_body())
            elif path == "/readyz":
                ready = bool(self.fe.engine.ready())
                self._send_json(200 if ready else 503,
                                {"schema_version": wire.WIRE_SCHEMA_VERSION,
                                 "ready": ready,
                                 "replica_id": self.fe.replica_id})
            elif path in ("/metrics", "/metrics.json"):
                self._metrics(path, query)
            else:
                self._send_json(404, {"error": {"type": "NotFound",
                                                "message": self.path}})

    def do_POST(self):
        with self._track():
            if self.path == "/v1/submit":
                self._submit()
            elif self.path == "/v1/generate":
                self._generate()
            else:
                self._send_json(404, {"error": {"type": "NotFound",
                                                "message": self.path}})

    def _metrics(self, path: str, query: str) -> None:
        """``GET /metrics`` — Prometheus text exposition (0.0.4) of the
        replica's registry; ``/metrics.json`` / ``?format=json`` is the
        schema-versioned JSON form with exemplars, SLO state and the
        tenant ledger. Refreshing ``engine.slo_state()`` first keeps the
        ``slo_burn_*`` gauges current in BOTH forms. No fault injection
        fires here (probe route — see ``_respond_best_effort``)."""
        from urllib.parse import parse_qs

        from . import telemetry

        fe = self.fe
        # getattr-guarded: a bare engine double (tests) without the SLO
        # tracker or tenant ledger still serves its registry
        slo = tenants = None
        slo_fn = getattr(fe.engine, "slo_state", None)
        if callable(slo_fn):
            slo = slo_fn()    # side effect: refreshes slo_burn_* gauges
        ten_fn = getattr(fe.engine, "tenant_accounting", None)
        if callable(ten_fn):
            tenants = ten_fn()
        fmt = (parse_qs(query).get("format") or [""])[0]
        if path.endswith(".json") or fmt == "json":
            body = telemetry.metrics_json(
                replica_id=fe.replica_id, slo=slo, tenants=tenants)
            self._send_raw(200, "application/json", wire.dumps(body))
        else:
            text = _monitor.get_registry().to_prometheus()
            self._send_raw(200, "text/plain; version=0.0.4; charset=utf-8",
                           text.encode("utf-8"))

    def _send_raw(self, status: int, content_type: str,
                  raw: bytes) -> None:
        try:
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(raw)))
            self.end_headers()
            self.wfile.write(raw)
        except (BrokenPipeError, ConnectionResetError, TimeoutError,
                OSError):
            logger.debug("fleet frontend: scraper gone before the "
                         "metrics body was written")

    def _track(self):
        fe = self.fe

        class _T:
            def __enter__(self_t):
                with fe._inflight_lock:
                    fe._inflight += 1

            def __exit__(self_t, *exc):
                with fe._inflight_lock:
                    fe._inflight -= 1
                return False

        return _T()

    # -- submit ----------------------------------------------------------
    def _submit(self) -> None:
        fe = self.fe
        t0 = time.monotonic()
        span = _trace.start_span("fleet.request", parent=self._trace_parent(),
                                 route="submit", replica=fe.replica_id)
        try:
            body = wire.loads(self._body())
            feed = wire.decode_feed(body.get("feed"))
            priority = wire.resolve_priority(body)
            deadline_s = body.get("deadline_s")
            fut = fe.engine.submit(
                feed, priority=priority,
                deadline_s=float(deadline_s)
                if deadline_s is not None else None,
                trace_parent=span if span else self._trace_parent(),
                tenant=wire.resolve_tenant(body))
        except Exception as e:
            # NOTHING was admitted (validation bug or a submit-time
            # typed rejection): the router may safely redispatch
            self._send_error("submit", span, e, admitted=False)
            return
        try:
            outs = fut.result(timeout=fe.config.request_timeout_s)
        except Exception as e:
            # the request WAS admitted; this typed outcome is final —
            # the admitted flag forbids a router retry even for
            # EngineStopped (stop-without-drain / dispatcher crash),
            # which at submit time would have been retryable
            self._send_error("submit", span, e, admitted=True)
            return
        # the engine-side outcome is settled: count/span close first so
        # a caller that hung up cannot double-count the request through
        # the error path — a failed WRITE is not a serving outcome
        span.set_attribute("outcome", "completed")
        span.end()
        fe._count("submit", "completed")
        fe._observe_latency(time.monotonic() - t0, "submit",
                            fut.trace_id)
        self._respond_best_effort(200,
                                  wire.encode_outputs(outs, fut.trace_id))

    def _respond_best_effort(self, status: int, obj: dict) -> None:
        """Write a response to a caller that may already be gone; a dead
        connection is logged, never re-routed into the error path (the
        engine-side outcome already holds). The ``wire_response`` fault
        site fires HERE (request responses only — the health probes stay
        clean, which is exactly what makes a stalling-but-listening
        replica the breaker's hard case): ``drop`` severs the connection
        before any byte, ``stall`` sleeps ``FLAGS_fault_stall_s`` first
        (the router times out and must eject this replica), ``corrupt``
        mangles the body bytes."""
        try:
            act = _faults.fault_action("wire_response")
            if act == "drop":
                logger.warning("fleet frontend: injected wire_response "
                               "drop — severing the connection")
                self.close_connection = True
                self.connection.close()
                return
            if act == "stall":
                _faults.stall()
            self._send_json(status, obj, corrupt=(act == "corrupt"))
        except (BrokenPipeError, ConnectionResetError, TimeoutError,
                OSError):
            logger.debug("fleet frontend: client gone before the "
                         "response was written")

    def _send_error(self, route: str, span, e: BaseException,
                    admitted: Optional[bool] = None) -> None:
        fe = self.fe
        span.end(error=e)
        outcome = type(e).__name__
        fe._count(route, outcome)
        if not isinstance(e, (ServingError, DeadlineExceeded, ValueError,
                              TimeoutError)):
            # engine bugs still answer structured (500) — but loudly
            logger.exception("fleet frontend: unexpected %s on /%s",
                             outcome, route)
        self._respond_best_effort(wire.status_for(e),
                                  wire.error_body(e, admitted=admitted))

    # -- generate (streaming) --------------------------------------------
    def _generate(self) -> None:
        fe = self.fe
        t0 = time.monotonic()
        span = _trace.start_span("fleet.request", parent=self._trace_parent(),
                                 route="generate", replica=fe.replica_id)
        if not isinstance(fe.engine, GenerativeEngine):
            err = wire.WireError("this replica serves request/response "
                                 "inference only (no /v1/generate)")
            self._send_error("generate", span, err)
            return
        try:
            body = wire.loads(self._body())
            prompt = body.get("prompt")
            if not isinstance(prompt, list) or not prompt:
                raise wire.WireError("generate body needs a non-empty "
                                     "'prompt' token list")
            deadline_s = body.get("deadline_s")
            fut = fe.engine.submit(
                [int(t) for t in prompt],
                max_new_tokens=body.get("max_new_tokens"),
                priority=wire.resolve_priority(body),
                deadline_s=float(deadline_s)
                if deadline_s is not None else None,
                trace_parent=span if span else self._trace_parent(),
                tenant=wire.resolve_tenant(body))
        except Exception as e:
            # nothing streamed yet: a plain typed error response, so the
            # router can still classify admitted vs unadmitted by status
            self._send_error("generate", span, e)
            return
        # admitted: from here the response is a 200 ND-JSON stream and
        # the typed outcome travels in the TERMINAL chunk
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header(wire.TRACE_HEADER,
                         f"{fut.trace_id}/" if fut.trace_id else "")
        self.end_headers()
        streamed = 0
        outcome: Optional[BaseException] = None
        try:
            for tok in fut.stream(timeout=fe.config.request_timeout_s):
                self._chunk({"tokens": [int(tok)]})
                streamed += 1
        except (ServingError, DeadlineExceeded) as e:
            outcome = e
        except (BrokenPipeError, ConnectionResetError):
            # caller hung up mid-stream; the engine still settles the
            # request exactly once — nothing more to write
            span.end(error=ConnectionError("client disconnected"))
            fe._count("generate", "client_disconnected")
            return
        except TimeoutError as e:
            outcome = e
        try:
            if outcome is None:
                self._chunk({"done": True, "outcome": "completed",
                             "tokens_streamed": streamed,
                             "trace_id": fut.trace_id})
                span.set_attribute("outcome", "completed")
                span.end()
                fe._count("generate", "completed")
            else:
                body = wire.error_body(outcome)
                body.update(done=True, tokens_streamed=streamed)
                self._chunk(body)
                span.end(error=outcome)
                fe._count("generate", type(outcome).__name__)
            self._chunk(None)   # chunked-encoding terminator
            fe._observe_latency(time.monotonic() - t0, "generate",
                                fut.trace_id)
            if _monitor.enabled() and streamed:
                _monitor.counter(
                    "fleet_stream_tokens_total",
                    "tokens delivered over streaming fleet responses"
                ).inc(streamed)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _chunk(self, obj: Optional[dict]) -> None:
        """One chunked-transfer frame (None = final empty chunk). The
        ``wire_stream`` fault site fires per frame: ``drop`` severs the
        stream mid-generation (the router delivers the partials, then a
        typed terminal), ``stall`` delays the frame, ``corrupt`` mangles
        it (the router classifies it typed instead of losing tokens)."""
        if obj is None:
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
            return
        act = _faults.fault_action("wire_stream")
        if act == "drop":
            logger.warning("fleet frontend: injected wire_stream drop — "
                           "severing the stream")
            self.close_connection = True
            self.connection.close()
            raise BrokenPipeError("[resilience] injected wire_stream drop")
        if act == "stall":
            _faults.stall()
        line = wire.dumps(obj) + b"\n"
        if act == "corrupt":
            line = b"\xff" + line[1:]
        self.wfile.write(f"{len(line):x}\r\n".encode("ascii") + line
                         + b"\r\n")
        self.wfile.flush()
