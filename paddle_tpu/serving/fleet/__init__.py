"""paddle_tpu.serving.fleet — the network serving tier above the engine.

PRs 8–11 built a production single-process engine (continuous batching,
typed outcomes, exact accounting, streaming generation); this package is
the layer the reference stack serves real traffic through
(``paddle/fluid/operators/distributed/`` + the serving fleet,
PAPER.md §L7) rebuilt on three parts:

* :mod:`~paddle_tpu.serving.fleet.frontend` — a stdlib HTTP/JSON server
  per replica: ``/v1/submit``, ``/v1/generate`` (chunked token
  streaming), ``/healthz`` (the frozen engine health schema) and
  ``/readyz``, speaking the versioned wire schema of
  :mod:`~paddle_tpu.serving.fleet.wire` (typed outcome -> distinct HTTP
  status, trace-ID headers, priority/SLO classes, deadlines).
* :mod:`~paddle_tpu.serving.fleet.router` — load-aware dispatch over N
  replicas from each replica's pressure snapshot, honoring per-replica
  drain, retrying *unadmitted* requests exactly once on a sibling, and
  extending the exactly-one-outcome invariant fleet-wide.
* warm start — replicas run with ``FLAGS_aot_cache_dir``
  (:mod:`paddle_tpu.aot_cache`): a cold replica loads serialized AOT
  executables instead of paying the compile storm, measured
  cold-vs-warm in ``ci_fleet_report.json``.

``python -m paddle_tpu.serving.fleet.replica`` runs one replica process
(model probe + warm-up + front-end + SIGTERM drain);
``tools/load_check.py --fleet`` is the CI gate. docs/SERVING.md "Fleet
tier" has the architecture, wire schema and routing policy.
"""
from __future__ import annotations

from .autoscaler import AutoscalerConfig, FleetAutoscaler
from .frontend import FrontendConfig, ServingFrontend
from .router import FleetRouter, Replica, RouterConfig
from .supervisor import (ReplicaCrashLoop, ReplicaSupervisor,
                         SupervisedReplica, SupervisorConfig)
from .telemetry import (METRICS_SCHEMA_VERSION, AggregatorConfig,
                        FleetAggregator, metrics_json)
from .wire import (SLO_CLASSES, TRACE_HEADER, WIRE_SCHEMA_VERSION,
                   ReplicaLost, WireError)

__all__ = [
    "ServingFrontend", "FrontendConfig", "FleetRouter", "Replica",
    "RouterConfig", "ReplicaSupervisor", "SupervisorConfig",
    "SupervisedReplica", "ReplicaCrashLoop", "ReplicaLost", "WireError",
    "WIRE_SCHEMA_VERSION", "TRACE_HEADER", "SLO_CLASSES",
    "FleetAggregator", "AggregatorConfig", "metrics_json",
    "METRICS_SCHEMA_VERSION", "FleetAutoscaler", "AutoscalerConfig",
]
