"""Load-aware replica router: dispatch over N fleet front-ends.

The fleet's brain (docs/SERVING.md "Fleet tier — routing policy"): a
:class:`FleetRouter` owns a set of replica addresses (each a
``ServingFrontend`` over its own engine process), keeps a **pressure
snapshot** per replica by polling ``/healthz`` (queue depth, breaker
state, degradation, readiness — the engine's frozen health schema), and
routes each request to the least-loaded READY replica.

The contract extends the engine's exactly-one-outcome invariant
fleet-wide:

* **drain honor** — a preempted replica (SIGTERM -> drain) flips
  ``ready()`` false; the router stops routing to it while its admitted
  requests finish. Nothing a replica admitted is ever shed by routing.
* **unadmitted retry, exactly once** — a dispatch the replica provably
  did NOT admit (connection refused before the request was sent, or a
  429 shed / 410 stopped rejection whose error body does not claim
  admission — :func:`~.wire.response_is_unadmitted`: the front-end's
  explicit ``admitted`` flag is authoritative, so an ADMITTED request
  that settled ``EngineStopped`` also travels as 410 but is never
  redispatched) is retried on ONE sibling. Anything possibly admitted
  is never retried: a connection that dies after the request went out
  settles as typed :class:`~.wire.ReplicaLost` — retrying it could
  give one request two outcomes.
* **capability-aware generate** — ``generate()`` routes only to
  replicas whose health advertises the generative capability (mixed
  fleets: a request/response replica answers /v1/generate with a 400
  caller bug, so the router never sends one there).
* **no hangs** — zero ready replicas is a typed
  :class:`~paddle_tpu.serving.Overloaded` (``reason="no_ready_replica"``)
  at submit, never a wait.
* **per-replica transport breaker** — consecutive transport failures
  (connect refused, connection died, request timeout, corrupt wire
  payload) eject a replica from routing (``serving.breaker`` reused per
  replica), so a stalling-but-listening replica stops eating
  ``request_timeout_s`` per request. Half-open probes ride the
  ``/healthz`` poll after the (doubling) cooldown; request traffic is
  never the probe. A corrupt 200 body or stream chunk is a typed
  :class:`~.wire.ReplicaLost`, never a silent empty/truncated result.
* **dynamic membership** — ``add_replica``/``remove_replica``/
  ``reassign_replica`` let the supervisor register a freshly (re)started
  replica (same id, new port) as fresh capacity within one poll.
* **trace propagation** — every dispatch carries the router's span
  context in ``X-PT-Trace``; the replica's request root joins it, so one
  trace id follows the request router -> frontend -> engine -> flight
  recorder and ``accounting()['recent_outcomes']`` on either side names
  the same id.

``accounting()`` is the fleet-wide ledger (the ``load_check --fleet``
gate's ground truth); metrics land on ``router_*``
(docs/OBSERVABILITY.md).
"""
from __future__ import annotations

import dataclasses
import http.client
import json
import logging
import socket
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ... import monitor as _monitor
from ... import trace as _trace
from ...resilience import faults as _faults
from ...resilience.deadline import DeadlineExceeded
from ..breaker import CLOSED, CircuitBreaker
from ..engine import Overloaded, ServingError
from . import wire
from .wire import ReplicaLost

__all__ = ["FleetRouter", "Replica", "RouterConfig"]

logger = logging.getLogger("paddle_tpu.serving.fleet")


@dataclasses.dataclass
class RouterConfig:
    """Routing knobs. ``honor_drain``/``retry_unadmitted`` exist so the
    CI gate's negative control can prove the gate detects a router
    without them — production routers keep both on."""

    poll_interval_s: float = 0.2
    connect_timeout_s: float = 5.0
    request_timeout_s: float = 120.0
    honor_drain: bool = True
    retry_unadmitted: bool = True
    # pressure score weights: being degraded or holding open breakers
    # outweighs a handful of queued requests
    degraded_penalty: int = 16
    open_bucket_penalty: int = 8
    # per-replica circuit breaker (serving.breaker reused): this many
    # CONSECUTIVE transport failures (connect refused, connection died,
    # request timeout, corrupt wire payload) eject the replica from
    # routing — a stalling-but-listening replica must not eat
    # request_timeout_s per request. Half-open probes ride the /healthz
    # poll after the cooldown (doubling backoff per re-open).
    breaker_threshold: int = 3
    breaker_cooldown_s: float = 1.0


class Replica:
    """One replica address + its last pressure snapshot."""

    def __init__(self, replica_id: str, host: str, port: int):
        self.replica_id = replica_id
        self.host = host
        self.port = int(port)
        self._lock = _monitor.make_lock("Replica._lock")
        # per-replica transport circuit breaker; attached/reset by the
        # router (its config carries the thresholds)
        self.breaker: Optional[CircuitBreaker] = None
        self._snap: Dict[str, Any] = self._fresh_snap()

    @staticmethod
    def _fresh_snap() -> Dict[str, Any]:
        return {"ok": False, "ready": False, "queue_depth": 0,
                "degraded": False, "open_buckets": 0, "generative": False,
                "status": "unknown", "slo_state": "unknown",
                "polled_at": 0.0}

    @property
    def address(self) -> str:
        host, port = self.endpoint()
        return f"{host}:{port}"

    def endpoint(self):
        """Atomic ``(host, port)`` snapshot — dispatch/poll must never
        observe a torn old-host/new-port pair across a ``reassign``."""
        with self._lock:
            return self.host, self.port

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._snap)

    def _update(self, snap: Dict[str, Any]) -> None:
        with self._lock:
            self._snap = snap

    def reassign(self, host: str, port: int) -> None:
        """Point this replica entry at a fresh process (same id, new
        port) — the supervisor's restart path. The stale pressure
        snapshot is dropped so the next poll decides readiness."""
        with self._lock:
            self.host = host
            self.port = int(port)
            self._snap = self._fresh_snap()

    def __repr__(self):
        return f"Replica({self.replica_id}@{self.address})"


_TERMINAL_KEYS = ("completed", "shed", "deadline_exceeded", "failed",
                  "circuit_open", "stopped", "replica_lost", "other_error")


class FleetRouter:
    """See module docstring. ``replicas``: ``Replica`` objects or
    ``(replica_id, host, port)`` tuples. ``start()`` begins background
    polling; ``submit``/``generate`` are thread-safe and blocking (run
    them from your own worker threads for concurrency, exactly like
    ``ServingEngine.submit`` callers)."""

    def __init__(self, replicas: Sequence = (),
                 config: Optional[RouterConfig] = None):
        self.config = config or RouterConfig()
        # an EMPTY fleet is legal since the supervisor era (replicas
        # register as they come ready); submits shed typed meanwhile
        self.replicas: List[Replica] = []
        self._lock = _monitor.make_lock("FleetRouter._lock")
        self._breaker_lock = _monitor.make_lock("FleetRouter._breaker_lock")
        for r in replicas:
            self.add_replica(r)
        self._rr = 0
        self._poll_thread: Optional[threading.Thread] = None
        # in-flight /healthz poll connections: stop() closes them when a
        # hung poll would otherwise outlive the join bound
        self._poll_conns: set = set()
        self._stop_ev = threading.Event()
        self._acct: Dict[str, int] = {"submitted": 0, "retries": 0}
        self._acct.update({k: 0 for k in _TERMINAL_KEYS})
        self._pending = 0

    # -- fleet membership (the supervisor's registration surface) --------
    def _new_breaker(self, replica_id: str) -> CircuitBreaker:
        # emit_transitions=False: the router owns its own
        # router_breaker_transitions_total; the serving metric must keep
        # meaning BUCKET breakers
        return CircuitBreaker(self.config.breaker_threshold,
                              self.config.breaker_cooldown_s,
                              name=replica_id, emit_transitions=False)

    def add_replica(self, replica) -> Replica:
        """Register one replica (``Replica`` or ``(id, host, port)``).
        Thread-safe; a duplicate id is a caller bug."""
        r = replica if isinstance(replica, Replica) else Replica(*replica)
        r.breaker = self._new_breaker(r.replica_id)
        with self._lock:
            if any(x.replica_id == r.replica_id for x in self.replicas):
                raise ValueError(f"fleet router: replica id "
                                 f"'{r.replica_id}' already registered")
            # replace the list wholesale: _pick/poll iterate without the
            # lock and must never see a half-mutated list
            self.replicas = self.replicas + [r]
        return r

    def remove_replica(self, replica_id: str) -> Optional[Replica]:
        """Deregister (a retired/drained replica). Returns the removed
        entry, or ``None`` when unknown."""
        with self._lock:
            found = next((r for r in self.replicas
                          if r.replica_id == replica_id), None)
            if found is not None:
                self.replicas = [r for r in self.replicas
                                 if r is not found]
        return found

    def get_replica(self, replica_id: str) -> Optional[Replica]:
        for r in self.replicas:
            if r.replica_id == replica_id:
                return r
        return None

    def reassign_replica(self, replica_id: str, host: str,
                         port: int) -> Replica:
        """A restarted replica (same id, NEW port) re-enters as fresh
        capacity: snapshot dropped, transport breaker reset — the next
        poll (the supervisor triggers one) makes it routable."""
        r = self.get_replica(replica_id)
        if r is None:
            return self.add_replica(Replica(replica_id, host, port))
        r.reassign(host, port)
        with self._breaker_lock:
            r.breaker = self._new_breaker(r.replica_id)
        return r

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "FleetRouter":
        if self._poll_thread is not None:
            return self
        self.poll_now()
        self._stop_ev.clear()
        self._poll_thread = threading.Thread(
            target=self._poll_loop, name="paddle_tpu-fleet-router-poll",
            daemon=True)
        self._poll_thread.start()
        return self

    def stop(self) -> None:
        self._stop_ev.set()
        t, self._poll_thread = self._poll_thread, None
        if t is not None:
            # bound teardown even when an in-flight /healthz poll is hung
            # on a stalled replica: give it one connect budget, then
            # close the socket under the read and join again
            t.join(self.config.connect_timeout_s)
            if t.is_alive():
                with self._lock:
                    conns = list(self._poll_conns)
                logger.warning(
                    "fleet router: poll thread still in a /healthz read "
                    "at stop() — closing %d in-flight poll socket(s)",
                    len(conns))
                for c in conns:
                    try:
                        c.close()   # closes the underlying socket too
                    except Exception:
                        pass
                t.join(2.0)
                if t.is_alive():
                    logger.error("fleet router: poll thread did not exit "
                                 "after its socket was closed")

    def __enter__(self) -> "FleetRouter":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- polling ---------------------------------------------------------
    def _poll_loop(self) -> None:
        while not self._stop_ev.wait(self.config.poll_interval_s):
            self.poll_now()

    def poll_now(self) -> None:
        """One synchronous poll of every replica's ``/healthz``. A
        healthy poll is also the transport breaker's HALF-OPEN probe: an
        ejected replica whose cooldown elapsed and whose health answers
        ready is readmitted here — no request traffic is risked on it."""
        ready = 0
        for r in self.replicas:
            snap = self._poll_one(r)
            r._update(snap)
            self._breaker_probe(r, bool(snap["ok"] and snap["ready"]))
            ready += bool(snap["ok"] and snap["ready"])
            if _monitor.enabled():
                _monitor.counter(
                    "router_polls_total",
                    "replica health polls by result").labels(
                    replica=r.replica_id,
                    result="ok" if snap["ok"] else "error").inc()
        if _monitor.enabled():
            _monitor.gauge(
                "router_replicas_ready",
                "replicas currently ready for routing").set(ready)

    def _poll_one(self, r: Replica) -> Dict[str, Any]:
        host, port = r.endpoint()
        try:
            conn = http.client.HTTPConnection(
                host, port, timeout=self.config.connect_timeout_s)
            with self._lock:
                self._poll_conns.add(conn)
            try:
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                raw = resp.read()
            finally:
                with self._lock:
                    self._poll_conns.discard(conn)
                conn.close()
            # the /healthz body is the engine's FROZEN health schema —
            # its schema_version field is HEALTH_SCHEMA_VERSION, NOT the
            # request wire schema, so it must not go through
            # wire.loads()'s version gate: the router reads documented
            # keys and tolerates a replica speaking a newer health schema
            try:
                body = json.loads(raw.decode("utf-8"))
            except Exception:
                body = {}
            if not isinstance(body, dict):
                body = {}
            slo = body.get("slo") if isinstance(body.get("slo"),
                                               dict) else {}
            return {"ok": resp.status == 200,
                    "ready": bool(body.get("ready")),
                    "queue_depth": int(body.get("queue_depth", 0)),
                    "degraded": bool(body.get("degraded")),
                    "open_buckets": len(body.get("open_buckets") or ()),
                    "generative": bool(body.get("generative")),
                    "status": str(body.get("status", "unknown")),
                    "slo_state": str(slo.get("state", "unknown")),
                    "polled_at": time.monotonic()}
        except Exception as e:
            return {"ok": False, "ready": False, "queue_depth": 0,
                    "degraded": False, "open_buckets": 0,
                    "generative": False,
                    "status": f"unreachable:{type(e).__name__}",
                    "slo_state": "unknown",
                    "polled_at": time.monotonic()}

    # -- per-replica transport breaker -----------------------------------
    # serving.breaker.CircuitBreaker documents single-thread allow/record;
    # router dispatches run from arbitrary caller threads plus the poll
    # thread, so every breaker touch goes through _breaker_lock.

    def _breaker_note(self, r: Replica, before: str) -> None:
        after = r.breaker.state
        if after == before:
            return
        logger.warning("fleet router: replica %s transport breaker %s -> "
                       "%s", r.replica_id, before, after)
        if _monitor.enabled():
            _monitor.counter(
                "router_breaker_transitions_total",
                "per-replica transport breaker state changes").labels(
                replica=r.replica_id, to=after).inc()
            _monitor.gauge(
                "router_breaker_open_replicas",
                "replicas currently ejected by their transport breaker"
            ).set(sum(1 for x in self.replicas
                      if x.breaker is not None
                      and x.breaker.state != CLOSED))

    def _breaker_failure(self, r: Replica,
                         br: Optional[CircuitBreaker] = None) -> None:
        """``br`` (captured when the dispatch STARTED) pins the record
        to one incarnation: a straggler failing against a replica that
        was reassigned mid-flight must not eject the fresh restart."""
        with self._breaker_lock:
            if br is not None and r.breaker is not br:
                return
            before = r.breaker.state
            r.breaker.record_failure()
            self._breaker_note(r, before)

    def _breaker_success(self, r: Replica,
                         br: Optional[CircuitBreaker] = None) -> None:
        with self._breaker_lock:
            if br is not None and r.breaker is not br:
                return
            before = r.breaker.state
            r.breaker.record_success()
            self._breaker_note(r, before)

    def _breaker_admits(self, r: Replica) -> bool:
        """Routing admission: only a CLOSED breaker routes. Open and
        half-open replicas wait for the /healthz poll probe — request
        traffic is never the probe."""
        with self._breaker_lock:
            return r.breaker is None or r.breaker.state == CLOSED

    def _breaker_probe(self, r: Replica, healthy: bool) -> None:
        """The half-open probe riding one /healthz poll result: after
        the cooldown a healthy poll closes the breaker (fresh capacity),
        an unhealthy one re-opens it onto the next backoff rung."""
        if r.breaker is None or r.breaker.state == CLOSED:
            return
        with self._breaker_lock:
            before = r.breaker.state
            verdict = r.breaker.allow()
            # both legs are noted separately: a failed probe's
            # open -> half_open -> open pair would otherwise compare
            # equal and leave re-opens (and the doubling cooldown
            # ladder) invisible in logs and the transition counter
            self._breaker_note(r, before)
            if verdict != "probe":
                return
            before = r.breaker.state
            if healthy:
                r.breaker.record_success()
            else:
                r.breaker.record_failure()
            self._breaker_note(r, before)

    # -- routing policy --------------------------------------------------
    def _score(self, snap: Dict[str, Any]) -> int:
        return (int(snap["queue_depth"])
                + self.config.degraded_penalty * bool(snap["degraded"])
                + self.config.open_bucket_penalty
                * int(snap["open_buckets"]))

    def _pick(self, exclude: Sequence[Replica] = (),
              require_generative: bool = False) -> Optional[Replica]:
        """Least-loaded routable replica (drain-aware unless the negative
        control disabled it), round-robin among score ties. With
        ``require_generative`` only replicas whose health advertises the
        generative capability are candidates."""
        # ONE snapshot per replica: filters and score must read the same
        # poll (a concurrent poll-thread update between reads could pass
        # a replica no single poll considered routable)
        cands = [(r, r.snapshot()) for r in self.replicas
                 if r not in exclude and self._breaker_admits(r)]
        if require_generative:
            cands = [(r, s) for r, s in cands if s.get("generative")]
        if self.config.honor_drain:
            cands = [(r, s) for r, s in cands if s["ok"] and s["ready"]]
        if not cands:
            return None
        with self._lock:
            self._rr += 1
            rot = self._rr
        scored = sorted(
            ((self._score(s), (i + rot) % len(cands), r)
             for i, (r, s) in enumerate(cands)), key=lambda t: t[:2])
        return scored[0][2]

    # -- accounting ------------------------------------------------------
    def _note_submitted(self) -> None:
        with self._lock:
            self._acct["submitted"] += 1
            self._pending += 1

    def _note_outcome(self, key: str, replica: str = "") -> None:
        with self._lock:
            self._acct[key] += 1
            self._pending -= 1
        if _monitor.enabled():
            _monitor.counter(
                "router_dispatch_total",
                "fleet-wide request terminal outcomes by replica (the "
                "replica that produced the outcome; 'none' when no "
                "replica was reachable)").labels(
                replica=replica or "none", outcome=key).inc()

    def _note_retry(self, reason: str) -> None:
        with self._lock:
            self._acct["retries"] += 1
        if _monitor.enabled():
            _monitor.counter(
                "router_retries_total",
                "unadmitted dispatches retried on a sibling, by reason"
            ).labels(reason=reason).inc()

    def accounting(self) -> dict:
        """The fleet-wide ledger: ``submitted`` equals the sum of all
        terminal outcomes plus ``pending`` (requests currently inside a
        ``submit``/``generate`` call). The ``load_check --fleet`` gate's
        invariant. ``retries`` counts sibling redispatches — a retried
        request still reaches exactly ONE outcome."""
        with self._lock:
            acct = dict(self._acct)
            acct["pending"] = self._pending
        terminal = sum(acct[k] for k in _TERMINAL_KEYS)
        acct["accounted"] = terminal + acct["pending"]
        acct["exact"] = acct["accounted"] == acct["submitted"]
        return acct

    @staticmethod
    def _outcome_key(e: BaseException) -> str:
        if isinstance(e, Overloaded):
            return "shed"
        if isinstance(e, DeadlineExceeded):
            return "deadline_exceeded"
        if isinstance(e, ReplicaLost):
            return "replica_lost"
        from ..engine import BatchFailed, CircuitOpen, EngineStopped

        if isinstance(e, BatchFailed):
            return "failed"
        if isinstance(e, CircuitOpen):
            return "circuit_open"
        if isinstance(e, EngineStopped):
            return "stopped"
        return "other_error"

    # -- submit ----------------------------------------------------------
    def submit(self, feed: Dict[str, Any], *, priority: Optional[int] = None,
               slo_class: Optional[str] = None,
               deadline_s: Optional[float] = None,
               tenant: Optional[str] = None) -> List[np.ndarray]:
        """Route one request/response inference call. Returns the fetch
        rows, or raises the SAME typed outcome classes the in-process
        engine raises (reconstructed from the wire), plus
        :class:`ReplicaLost` for a replica that died holding an admitted
        request. Blocking; thread-safe."""
        body = {"schema_version": wire.WIRE_SCHEMA_VERSION,
                "feed": wire.encode_feed(feed)}
        if priority is not None:
            body["priority"] = int(priority)
        if slo_class is not None:
            body["slo_class"] = slo_class
        if deadline_s is not None:
            body["deadline_s"] = float(deadline_s)
        if tenant is not None:
            body["tenant"] = str(tenant)
        span = _trace.root_span("router.request", route="submit")
        self._note_submitted()
        t0 = time.monotonic()
        try:
            status, resp_body, replica = self._dispatch(
                "/v1/submit", body, span)
            if status == 200:
                try:
                    outs = wire.decode_outputs(resp_body)
                except wire.WireError as we:
                    # parseable JSON whose arrays don't decode is the
                    # same wire-integrity class as an unparseable body
                    raise ReplicaLost(
                        f"fleet: replica {replica} answered 200 with "
                        f"undecodable output arrays (wire corruption; "
                        f"request may have been admitted — not retried): "
                        f"{we}", replica=replica) from we
                span.set_attribute("outcome", "completed")
                span.set_attribute("replica", replica)
                span.end()
                self._note_outcome("completed", replica)
                if _monitor.enabled():
                    _monitor.histogram(
                        "router_request_seconds",
                        "end-to-end fleet request latency through the "
                        "router (completed requests; p50/p99 in the "
                        "snapshot)").observe(time.monotonic() - t0)
                return outs
            err = wire.error_from_body(resp_body,
                                       f"replica {replica} status {status}")
            err.replica = replica   # outcome attribution in the ledger
            raise err
        except BaseException as e:
            self._note_outcome(self._outcome_key(e),
                               getattr(e, "replica", ""))
            span.end(error=e)
            raise

    def _route_with_retry(self, attempt, *, generative: bool = False):
        """The unadmitted-retry policy, shared by ``submit`` and
        ``generate`` dispatch. ``attempt(replica)`` runs ONE dispatch
        attempt and classifies it:

        * ``("final", value)``          — terminal: ``value()`` is
          returned (or raises the typed outcome it closes over).
        * ``("reject", status, value)`` — the replica answered with a
          rejection :func:`wire.response_is_unadmitted` classified
          retryable (the front-end's explicit ``admitted`` flag is
          authoritative over the status map, so an ADMITTED request
          that settled ``EngineStopped`` — also a 410 — is never
          redispatched). Retried once, else ``value()``.
        * ``("unadmitted", exc)``       — provably never received
          (connection refused before any bytes moved). Retried once,
          else typed :class:`ReplicaLost`.
        * ``("lost", exc)``             — sent, then the connection
          died: possibly admitted, NEVER retried —
          :class:`ReplicaLost`.
        """
        tried: List[Replica] = []
        while True:
            r = self._pick(exclude=tried, require_generative=generative)
            if r is None:
                if tried:
                    # the retry also found nobody: surface the original
                    # rejection class as a shed (still typed)
                    raise Overloaded(
                        "fleet: no sibling available for unadmitted "
                        "retry", reason="no_ready_replica")
                if generative and self._pick() is not None:
                    raise Overloaded(
                        "fleet: no generative replica (the ready "
                        "replicas serve request/response only)",
                        reason="no_generative_replica")
                raise Overloaded(
                    "fleet: no ready replica (all draining, dead or "
                    "unreachable)", reason="no_ready_replica")
            outcome = attempt(r)
            kind = outcome[0]
            if kind == "final":
                return outcome[1]()
            if kind == "reject":
                _, status, value = outcome
                if self.config.retry_unadmitted and not tried:
                    tried.append(r)
                    self._note_retry(f"status_{status}")
                    continue
                return value()
            if kind == "unadmitted":
                _, exc = outcome
                if self.config.retry_unadmitted and not tried:
                    tried.append(r)
                    self._note_retry("connect_error")
                    continue
                raise ReplicaLost(
                    f"fleet: replica {r.replica_id} unreachable and "
                    f"retry exhausted: {exc}", replica=r.replica_id)
            if kind == "corrupt":
                # an undecodable body on a 200 (the request may have
                # been admitted AND completed replica-side) or on a
                # status whose authoritative admitted flag is unreadable:
                # never retried, never a silent empty result
                _, exc, status = outcome
                raise ReplicaLost(
                    f"fleet: replica {r.replica_id} answered {status} "
                    f"with an undecodable body (wire corruption; request "
                    f"may have been admitted — not retried): {exc}",
                    replica=r.replica_id)
            # kind == "lost": possibly admitted — never retried
            _, exc = outcome
            raise ReplicaLost(
                f"fleet: replica {r.replica_id} connection died after "
                f"the request was sent (request may have been admitted; "
                f"not retried): {exc}", replica=r.replica_id)

    def _dispatch(self, path: str, body: dict,
                  span) -> Tuple[int, dict, str]:
        """POST with the unadmitted-retry policy. Returns
        ``(status, body, replica_id)``; raises typed on transport-level
        outcomes (no replica / replica lost)."""
        def attempt(r: Replica):
            outcome = self._post_once(r, path, body, span)
            if outcome[0] != "response":
                return outcome
            _, status, resp_body = outcome
            value = lambda: (status, resp_body, r.replica_id)
            if wire.response_is_unadmitted(status, resp_body):
                return ("reject", status, value)
            return ("final", value)

        return self._route_with_retry(attempt)

    def _connect_and_post(self, r: Replica, path: str, body: dict, span):
        """Connect + POST one attempt, stopping at response HEADERS.
        Returns ``("conn", conn, resp)`` on any HTTP response (the
        caller owns and closes ``conn``), else the transport
        classification of :meth:`_route_with_retry`:
        ``("unadmitted", exc)`` — provably never received it;
        ``("lost", exc)``       — sent, then the connection died.
        Both transport failures feed the replica's circuit breaker.

        Chaos: the ``wire_connect`` fault site fires HERE, before any
        request bytes move — ``drop`` severs the dial (unadmitted, so
        the sibling retry must absorb it), ``stall`` delays the dial,
        ``corrupt`` mangles the request payload (the replica answers a
        400 the retry policy classifies unadmitted)."""
        payload = wire.dumps(body)
        br0 = r.breaker          # this dispatch's incarnation
        host, port = r.endpoint()    # atomic across a reassign
        conn = http.client.HTTPConnection(
            host, port, timeout=self.config.request_timeout_s)
        try:
            act = _faults.fault_action("wire_connect")
            if act == "drop":
                raise ConnectionRefusedError(
                    "[resilience] injected wire_connect drop")
            if act == "stall":
                _faults.stall()
            elif act == "corrupt":
                payload = b"\xff\x00corrupt" + payload[9:]
            # explicit connect with its own (short) timeout so a dead
            # replica is classified BEFORE any request bytes move
            conn.sock = socket.create_connection(
                (host, port), timeout=self.config.connect_timeout_s)
            conn.sock.settimeout(self.config.request_timeout_s)
        except OSError as e:
            conn.close()
            self._breaker_failure(r, br0)
            return ("unadmitted", e)
        headers = {"Content-Type": "application/json"}
        if span and span.trace_id:
            headers[wire.TRACE_HEADER] = span.context.to_wire()
        try:
            conn.request("POST", path, body=payload, headers=headers)
            resp = conn.getresponse()
        except (OSError, http.client.HTTPException) as e:
            conn.close()
            self._breaker_failure(r, br0)
            return ("lost", e)
        return ("conn", conn, resp)

    def _post_once(self, r: Replica, path: str, body: dict, span):
        """One POST attempt, read to the end of the body, classified:
        ``("response", status, body)`` — the replica answered;
        ``("corrupt", exc)`` — the replica answered 200 with an
        undecodable body (a wire-integrity failure: the request may have
        been admitted AND completed replica-side, so it is surfaced as a
        typed :class:`ReplicaLost`, never a silent empty result); else
        the transport classifications of :meth:`_connect_and_post`.
        The ``wire_response`` fault site fires around the body read."""
        br0 = r.breaker          # this dispatch's incarnation
        out = self._connect_and_post(r, path, body, span)
        if out[0] != "conn":
            return out
        _, conn, resp = out
        try:
            act = _faults.fault_action("wire_response")
            if act == "drop":
                self._breaker_failure(r, br0)
                return ("lost", ConnectionResetError(
                    "[resilience] injected wire_response drop"))
            if act == "stall":
                _faults.stall()
            try:
                raw = resp.read()
            except (OSError, http.client.HTTPException) as e:
                self._breaker_failure(r, br0)
                return ("lost", e)
            if act == "corrupt" and raw:
                raw = b"\xff" + raw[1:]
            try:
                parsed = wire.loads(raw) if raw else {}
            except wire.WireError as we:
                # an undecodable body is a wire-integrity failure. For a
                # 200, or for a status the retry policy would redispatch
                # (the body's AUTHORITATIVE admitted flag is unreadable —
                # an admitted EngineStopped travels as 410 too), guessing
                # could give one request two outcomes: typed ReplicaLost,
                # never retried. Other statuses are final either way and
                # degrade to the status map.
                self._breaker_failure(r, br0)
                if resp.status == 200 \
                        or resp.status in wire.UNADMITTED_STATUSES:
                    return ("corrupt", we, resp.status)
                return ("response", resp.status, {})
            self._breaker_success(r, br0)
            return ("response", resp.status, parsed)
        finally:
            conn.close()

    # -- generate (streaming) --------------------------------------------
    def generate(self, prompt, *, max_new_tokens: Optional[int] = None,
                 priority: Optional[int] = None,
                 slo_class: Optional[str] = None,
                 deadline_s: Optional[float] = None,
                 tenant: Optional[str] = None) -> Iterator[int]:
        """Route one generation request and stream its tokens. The
        returned iterator yields ints as the replica emits them and ends
        with normal exhaustion on completion — or raises the typed
        terminal outcome AFTER the partial tokens (a replica that drains
        or dies mid-stream delivers what it produced, then the typed
        error; :class:`ReplicaLost` when the connection died). Dispatch
        and the unadmitted-retry decision happen eagerly in this call;
        consume the iterator to completion for exact accounting."""
        body: Dict[str, Any] = {
            "schema_version": wire.WIRE_SCHEMA_VERSION,
            "prompt": [int(t) for t in np.asarray(prompt).reshape(-1)],
        }
        if max_new_tokens is not None:
            body["max_new_tokens"] = int(max_new_tokens)
        if priority is not None:
            body["priority"] = int(priority)
        if slo_class is not None:
            body["slo_class"] = slo_class
        if deadline_s is not None:
            body["deadline_s"] = float(deadline_s)
        if tenant is not None:
            body["tenant"] = str(tenant)
        span = _trace.root_span("router.request", route="generate")
        self._note_submitted()
        t0 = time.monotonic()
        try:
            conn, resp, replica = self._open_stream(body, span)
        except BaseException as e:
            self._note_outcome(self._outcome_key(e),
                               getattr(e, "replica", ""))
            span.end(error=e)
            raise
        return self._stream_tokens(conn, resp, replica, span, t0)

    def _open_stream(self, body, span):
        """Dispatch /v1/generate with the same unadmitted-retry policy
        as submit, stopping at response HEADERS (the body streams).
        Routed only to replicas advertising the generative capability."""
        def attempt(r: Replica):
            br0 = r.breaker      # this dispatch's incarnation
            out = self._connect_and_post(r, "/v1/generate", body, span)
            if out[0] != "conn":
                return out
            _, conn, resp = out
            if resp.status == 200:
                return ("final", lambda: (conn, resp, r))
            try:
                raw = resp.read()
            except (OSError, http.client.HTTPException):
                raw = b""
            conn.close()
            try:
                parsed = wire.loads(raw) if raw else {}
            except wire.WireError as we:
                # same wire-integrity rule as _post_once: a corrupt body
                # on a status the retry policy would redispatch hides
                # the authoritative admitted flag — never guess
                if resp.status in wire.UNADMITTED_STATUSES:
                    self._breaker_failure(r, br0)
                    return ("corrupt", we, resp.status)
                parsed = {}

            def raise_typed(parsed=parsed, status=resp.status):
                raise wire.error_from_body(
                    parsed, f"replica {r.replica_id} status {status}")

            if wire.response_is_unadmitted(resp.status, parsed):
                return ("reject", resp.status, raise_typed)
            return ("final", raise_typed)

        return self._route_with_retry(attempt, generative=True)

    def _stream_tokens(self, conn, resp, replica: Replica,
                       span, t0: float) -> Iterator[int]:
        streamed = 0
        br0 = replica.breaker    # this stream's incarnation
        outcome_err: Optional[BaseException] = None
        done = False
        try:
            while True:
                # the wire_stream fault site fires once per chunk read:
                # drop severs the stream, stall delays it, corrupt
                # mangles the chunk (hardened below into a typed loss)
                act = _faults.fault_action("wire_stream")
                if act == "drop":
                    self._breaker_failure(replica, br0)
                    outcome_err = ReplicaLost(
                        f"fleet: replica {replica.replica_id} stream "
                        f"dropped (injected) after {streamed} token(s)",
                        replica=replica.replica_id)
                    break
                if act == "stall":
                    _faults.stall()
                try:
                    line = resp.readline()
                except (OSError, http.client.HTTPException) as e:
                    self._breaker_failure(replica, br0)
                    outcome_err = ReplicaLost(
                        f"fleet: replica {replica.replica_id} died "
                        f"mid-stream after {streamed} token(s): {e}",
                        replica=replica.replica_id)
                    break
                if act == "corrupt":
                    # BEFORE the EOF check: a fired corrupt action must
                    # perform even when it lands on the terminating read
                    # (fired == performed, the audit-trail contract)
                    line = b"\xff" + line[1:]
                if not line:
                    if not done:
                        self._breaker_failure(replica, br0)
                        outcome_err = ReplicaLost(
                            f"fleet: replica {replica.replica_id} closed "
                            f"the stream without a terminal chunk "
                            f"({streamed} token(s) delivered)",
                            replica=replica.replica_id)
                    break
                try:
                    obj = wire.loads(line)
                except wire.WireError as we:
                    # an unparseable chunk is wire corruption, not noise:
                    # skipping it would silently lose tokens. Partials
                    # already yielded stand; the stream dies typed.
                    self._breaker_failure(replica, br0)
                    outcome_err = ReplicaLost(
                        f"fleet: replica {replica.replica_id} sent a "
                        f"corrupt stream chunk after {streamed} "
                        f"token(s) (not retried): {we}",
                        replica=replica.replica_id)
                    break
                if obj.get("done"):
                    done = True
                    if obj.get("error"):
                        outcome_err = wire.error_from_body(obj)
                    break
                for t in obj.get("tokens", ()):
                    streamed += 1
                    yield int(t)
        finally:
            conn.close()
            if outcome_err is None and not done:
                # generator closed early by the caller: the replica-side
                # outcome still lands; fleet-wide this call is abandoned
                outcome_err = ReplicaLost(
                    f"fleet: generate stream abandoned by the caller "
                    f"after {streamed} token(s)",
                    replica=replica.replica_id)
            if outcome_err is not None:
                self._note_outcome(self._outcome_key(outcome_err),
                                   replica.replica_id)
                span.end(error=outcome_err)
            else:
                self._breaker_success(replica, br0)
                span.set_attribute("outcome", "completed")
                span.set_attribute("replica", replica.replica_id)
                span.end()
                self._note_outcome("completed", replica.replica_id)
                if _monitor.enabled():
                    _monitor.histogram(
                        "router_request_seconds",
                        "end-to-end fleet request latency through the "
                        "router (completed requests; p50/p99 in the "
                        "snapshot)").observe(time.monotonic() - t0)
            if _monitor.enabled() and streamed:
                _monitor.counter(
                    "fleet_stream_tokens_total",
                    "tokens delivered over streaming fleet responses"
                ).inc(streamed)
        if outcome_err is not None:
            raise outcome_err
