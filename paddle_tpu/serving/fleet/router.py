"""Load-aware replica router: dispatch over N fleet front-ends.

The fleet's brain (docs/SERVING.md "Fleet tier — routing policy"): a
:class:`FleetRouter` owns a set of replica addresses (each a
``ServingFrontend`` over its own engine process), keeps a **pressure
snapshot** per replica by polling ``/healthz`` (queue depth, breaker
state, degradation, readiness — the engine's frozen health schema), and
routes each request to the least-loaded READY replica.

The contract extends the engine's exactly-one-outcome invariant
fleet-wide:

* **drain honor** — a preempted replica (SIGTERM -> drain) flips
  ``ready()`` false; the router stops routing to it while its admitted
  requests finish. Nothing a replica admitted is ever shed by routing.
* **unadmitted retry, exactly once** — a dispatch the replica provably
  did NOT admit (connection refused before the request was sent, or a
  429 shed / 410 stopped rejection whose error body does not claim
  admission — :func:`~.wire.response_is_unadmitted`: the front-end's
  explicit ``admitted`` flag is authoritative, so an ADMITTED request
  that settled ``EngineStopped`` also travels as 410 but is never
  redispatched) is retried on ONE sibling. Anything possibly admitted
  is never retried: a connection that dies after the request went out
  settles as typed :class:`~.wire.ReplicaLost` — retrying it could
  give one request two outcomes.
* **capability-aware generate** — ``generate()`` routes only to
  replicas whose health advertises the generative capability (mixed
  fleets: a request/response replica answers /v1/generate with a 400
  caller bug, so the router never sends one there).
* **no hangs** — zero ready replicas is a typed
  :class:`~paddle_tpu.serving.Overloaded` (``reason="no_ready_replica"``)
  at submit, never a wait.
* **trace propagation** — every dispatch carries the router's span
  context in ``X-PT-Trace``; the replica's request root joins it, so one
  trace id follows the request router -> frontend -> engine -> flight
  recorder and ``accounting()['recent_outcomes']`` on either side names
  the same id.

``accounting()`` is the fleet-wide ledger (the ``load_check --fleet``
gate's ground truth); metrics land on ``router_*``
(docs/OBSERVABILITY.md).
"""
from __future__ import annotations

import dataclasses
import http.client
import json
import logging
import socket
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ... import monitor as _monitor
from ... import trace as _trace
from ...resilience.deadline import DeadlineExceeded
from ..engine import Overloaded, ServingError
from . import wire
from .wire import ReplicaLost

__all__ = ["FleetRouter", "Replica", "RouterConfig"]

logger = logging.getLogger("paddle_tpu.serving.fleet")


@dataclasses.dataclass
class RouterConfig:
    """Routing knobs. ``honor_drain``/``retry_unadmitted`` exist so the
    CI gate's negative control can prove the gate detects a router
    without them — production routers keep both on."""

    poll_interval_s: float = 0.2
    connect_timeout_s: float = 5.0
    request_timeout_s: float = 120.0
    honor_drain: bool = True
    retry_unadmitted: bool = True
    # pressure score weights: being degraded or holding open breakers
    # outweighs a handful of queued requests
    degraded_penalty: int = 16
    open_bucket_penalty: int = 8


class Replica:
    """One replica address + its last pressure snapshot."""

    def __init__(self, replica_id: str, host: str, port: int):
        self.replica_id = replica_id
        self.host = host
        self.port = int(port)
        self._lock = threading.Lock()
        self._snap: Dict[str, Any] = {"ok": False, "ready": False,
                                      "queue_depth": 0, "degraded": False,
                                      "open_buckets": 0, "generative": False,
                                      "status": "unknown", "polled_at": 0.0}

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._snap)

    def _update(self, snap: Dict[str, Any]) -> None:
        with self._lock:
            self._snap = snap

    def __repr__(self):
        return f"Replica({self.replica_id}@{self.address})"


_TERMINAL_KEYS = ("completed", "shed", "deadline_exceeded", "failed",
                  "circuit_open", "stopped", "replica_lost", "other_error")


class FleetRouter:
    """See module docstring. ``replicas``: ``Replica`` objects or
    ``(replica_id, host, port)`` tuples. ``start()`` begins background
    polling; ``submit``/``generate`` are thread-safe and blocking (run
    them from your own worker threads for concurrency, exactly like
    ``ServingEngine.submit`` callers)."""

    def __init__(self, replicas: Sequence,
                 config: Optional[RouterConfig] = None):
        self.replicas: List[Replica] = [
            r if isinstance(r, Replica) else Replica(*r) for r in replicas]
        if not self.replicas:
            raise ValueError("fleet router needs at least one replica")
        self.config = config or RouterConfig()
        self._lock = threading.Lock()
        self._rr = 0
        self._poll_thread: Optional[threading.Thread] = None
        self._stop_ev = threading.Event()
        self._acct: Dict[str, int] = {"submitted": 0, "retries": 0}
        self._acct.update({k: 0 for k in _TERMINAL_KEYS})
        self._pending = 0

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "FleetRouter":
        if self._poll_thread is not None:
            return self
        self.poll_now()
        self._stop_ev.clear()
        self._poll_thread = threading.Thread(
            target=self._poll_loop, name="paddle_tpu-fleet-router-poll",
            daemon=True)
        self._poll_thread.start()
        return self

    def stop(self) -> None:
        self._stop_ev.set()
        t, self._poll_thread = self._poll_thread, None
        if t is not None:
            t.join(5.0)

    def __enter__(self) -> "FleetRouter":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- polling ---------------------------------------------------------
    def _poll_loop(self) -> None:
        while not self._stop_ev.wait(self.config.poll_interval_s):
            self.poll_now()

    def poll_now(self) -> None:
        """One synchronous poll of every replica's ``/healthz``."""
        ready = 0
        for r in self.replicas:
            snap = self._poll_one(r)
            r._update(snap)
            ready += bool(snap["ok"] and snap["ready"])
            if _monitor.enabled():
                _monitor.counter(
                    "router_polls_total",
                    "replica health polls by result").labels(
                    replica=r.replica_id,
                    result="ok" if snap["ok"] else "error").inc()
        if _monitor.enabled():
            _monitor.gauge(
                "router_replicas_ready",
                "replicas currently ready for routing").set(ready)

    def _poll_one(self, r: Replica) -> Dict[str, Any]:
        try:
            conn = http.client.HTTPConnection(
                r.host, r.port, timeout=self.config.connect_timeout_s)
            try:
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                raw = resp.read()
            finally:
                conn.close()
            # the /healthz body is the engine's FROZEN health schema —
            # its schema_version field is HEALTH_SCHEMA_VERSION, NOT the
            # request wire schema, so it must not go through
            # wire.loads()'s version gate: the router reads documented
            # keys and tolerates a replica speaking a newer health schema
            try:
                body = json.loads(raw.decode("utf-8"))
            except Exception:
                body = {}
            if not isinstance(body, dict):
                body = {}
            return {"ok": resp.status == 200,
                    "ready": bool(body.get("ready")),
                    "queue_depth": int(body.get("queue_depth", 0)),
                    "degraded": bool(body.get("degraded")),
                    "open_buckets": len(body.get("open_buckets") or ()),
                    "generative": bool(body.get("generative")),
                    "status": str(body.get("status", "unknown")),
                    "polled_at": time.monotonic()}
        except Exception as e:
            return {"ok": False, "ready": False, "queue_depth": 0,
                    "degraded": False, "open_buckets": 0,
                    "generative": False,
                    "status": f"unreachable:{type(e).__name__}",
                    "polled_at": time.monotonic()}

    # -- routing policy --------------------------------------------------
    def _score(self, snap: Dict[str, Any]) -> int:
        return (int(snap["queue_depth"])
                + self.config.degraded_penalty * bool(snap["degraded"])
                + self.config.open_bucket_penalty
                * int(snap["open_buckets"]))

    def _pick(self, exclude: Sequence[Replica] = (),
              require_generative: bool = False) -> Optional[Replica]:
        """Least-loaded routable replica (drain-aware unless the negative
        control disabled it), round-robin among score ties. With
        ``require_generative`` only replicas whose health advertises the
        generative capability are candidates."""
        # ONE snapshot per replica: filters and score must read the same
        # poll (a concurrent poll-thread update between reads could pass
        # a replica no single poll considered routable)
        cands = [(r, r.snapshot()) for r in self.replicas
                 if r not in exclude]
        if require_generative:
            cands = [(r, s) for r, s in cands if s.get("generative")]
        if self.config.honor_drain:
            cands = [(r, s) for r, s in cands if s["ok"] and s["ready"]]
        if not cands:
            return None
        with self._lock:
            self._rr += 1
            rot = self._rr
        scored = sorted(
            ((self._score(s), (i + rot) % len(cands), r)
             for i, (r, s) in enumerate(cands)), key=lambda t: t[:2])
        return scored[0][2]

    # -- accounting ------------------------------------------------------
    def _note_submitted(self) -> None:
        with self._lock:
            self._acct["submitted"] += 1
            self._pending += 1

    def _note_outcome(self, key: str, replica: str = "") -> None:
        with self._lock:
            self._acct[key] += 1
            self._pending -= 1
        if _monitor.enabled():
            _monitor.counter(
                "router_dispatch_total",
                "fleet-wide request terminal outcomes by replica (the "
                "replica that produced the outcome; 'none' when no "
                "replica was reachable)").labels(
                replica=replica or "none", outcome=key).inc()

    def _note_retry(self, reason: str) -> None:
        with self._lock:
            self._acct["retries"] += 1
        if _monitor.enabled():
            _monitor.counter(
                "router_retries_total",
                "unadmitted dispatches retried on a sibling, by reason"
            ).labels(reason=reason).inc()

    def accounting(self) -> dict:
        """The fleet-wide ledger: ``submitted`` equals the sum of all
        terminal outcomes plus ``pending`` (requests currently inside a
        ``submit``/``generate`` call). The ``load_check --fleet`` gate's
        invariant. ``retries`` counts sibling redispatches — a retried
        request still reaches exactly ONE outcome."""
        with self._lock:
            acct = dict(self._acct)
            acct["pending"] = self._pending
        terminal = sum(acct[k] for k in _TERMINAL_KEYS)
        acct["accounted"] = terminal + acct["pending"]
        acct["exact"] = acct["accounted"] == acct["submitted"]
        return acct

    @staticmethod
    def _outcome_key(e: BaseException) -> str:
        if isinstance(e, Overloaded):
            return "shed"
        if isinstance(e, DeadlineExceeded):
            return "deadline_exceeded"
        if isinstance(e, ReplicaLost):
            return "replica_lost"
        from ..engine import BatchFailed, CircuitOpen, EngineStopped

        if isinstance(e, BatchFailed):
            return "failed"
        if isinstance(e, CircuitOpen):
            return "circuit_open"
        if isinstance(e, EngineStopped):
            return "stopped"
        return "other_error"

    # -- submit ----------------------------------------------------------
    def submit(self, feed: Dict[str, Any], *, priority: Optional[int] = None,
               slo_class: Optional[str] = None,
               deadline_s: Optional[float] = None) -> List[np.ndarray]:
        """Route one request/response inference call. Returns the fetch
        rows, or raises the SAME typed outcome classes the in-process
        engine raises (reconstructed from the wire), plus
        :class:`ReplicaLost` for a replica that died holding an admitted
        request. Blocking; thread-safe."""
        body = {"schema_version": wire.WIRE_SCHEMA_VERSION,
                "feed": wire.encode_feed(feed)}
        if priority is not None:
            body["priority"] = int(priority)
        if slo_class is not None:
            body["slo_class"] = slo_class
        if deadline_s is not None:
            body["deadline_s"] = float(deadline_s)
        span = _trace.root_span("router.request", route="submit")
        self._note_submitted()
        t0 = time.monotonic()
        try:
            status, resp_body, replica = self._dispatch(
                "/v1/submit", body, span)
            if status == 200:
                outs = wire.decode_outputs(resp_body)
                span.set_attribute("outcome", "completed")
                span.set_attribute("replica", replica)
                span.end()
                self._note_outcome("completed", replica)
                if _monitor.enabled():
                    _monitor.histogram(
                        "router_request_seconds",
                        "end-to-end fleet request latency through the "
                        "router (completed requests; p50/p99 in the "
                        "snapshot)").observe(time.monotonic() - t0)
                return outs
            err = wire.error_from_body(resp_body,
                                       f"replica {replica} status {status}")
            err.replica = replica   # outcome attribution in the ledger
            raise err
        except BaseException as e:
            self._note_outcome(self._outcome_key(e),
                               getattr(e, "replica", ""))
            span.end(error=e)
            raise

    def _route_with_retry(self, attempt, *, generative: bool = False):
        """The unadmitted-retry policy, shared by ``submit`` and
        ``generate`` dispatch. ``attempt(replica)`` runs ONE dispatch
        attempt and classifies it:

        * ``("final", value)``          — terminal: ``value()`` is
          returned (or raises the typed outcome it closes over).
        * ``("reject", status, value)`` — the replica answered with a
          rejection :func:`wire.response_is_unadmitted` classified
          retryable (the front-end's explicit ``admitted`` flag is
          authoritative over the status map, so an ADMITTED request
          that settled ``EngineStopped`` — also a 410 — is never
          redispatched). Retried once, else ``value()``.
        * ``("unadmitted", exc)``       — provably never received
          (connection refused before any bytes moved). Retried once,
          else typed :class:`ReplicaLost`.
        * ``("lost", exc)``             — sent, then the connection
          died: possibly admitted, NEVER retried —
          :class:`ReplicaLost`.
        """
        tried: List[Replica] = []
        while True:
            r = self._pick(exclude=tried, require_generative=generative)
            if r is None:
                if tried:
                    # the retry also found nobody: surface the original
                    # rejection class as a shed (still typed)
                    raise Overloaded(
                        "fleet: no sibling available for unadmitted "
                        "retry", reason="no_ready_replica")
                if generative and self._pick() is not None:
                    raise Overloaded(
                        "fleet: no generative replica (the ready "
                        "replicas serve request/response only)",
                        reason="no_generative_replica")
                raise Overloaded(
                    "fleet: no ready replica (all draining, dead or "
                    "unreachable)", reason="no_ready_replica")
            outcome = attempt(r)
            kind = outcome[0]
            if kind == "final":
                return outcome[1]()
            if kind == "reject":
                _, status, value = outcome
                if self.config.retry_unadmitted and not tried:
                    tried.append(r)
                    self._note_retry(f"status_{status}")
                    continue
                return value()
            if kind == "unadmitted":
                _, exc = outcome
                if self.config.retry_unadmitted and not tried:
                    tried.append(r)
                    self._note_retry("connect_error")
                    continue
                raise ReplicaLost(
                    f"fleet: replica {r.replica_id} unreachable and "
                    f"retry exhausted: {exc}", replica=r.replica_id)
            # kind == "lost": possibly admitted — never retried
            _, exc = outcome
            raise ReplicaLost(
                f"fleet: replica {r.replica_id} connection died after "
                f"the request was sent (request may have been admitted; "
                f"not retried): {exc}", replica=r.replica_id)

    def _dispatch(self, path: str, body: dict,
                  span) -> Tuple[int, dict, str]:
        """POST with the unadmitted-retry policy. Returns
        ``(status, body, replica_id)``; raises typed on transport-level
        outcomes (no replica / replica lost)."""
        def attempt(r: Replica):
            outcome = self._post_once(r, path, body, span)
            if outcome[0] != "response":
                return outcome
            _, status, resp_body = outcome
            value = lambda: (status, resp_body, r.replica_id)
            if wire.response_is_unadmitted(status, resp_body):
                return ("reject", status, value)
            return ("final", value)

        return self._route_with_retry(attempt)

    def _connect_and_post(self, r: Replica, path: str, body: dict, span):
        """Connect + POST one attempt, stopping at response HEADERS.
        Returns ``("conn", conn, resp)`` on any HTTP response (the
        caller owns and closes ``conn``), else the transport
        classification of :meth:`_route_with_retry`:
        ``("unadmitted", exc)`` — provably never received it;
        ``("lost", exc)``       — sent, then the connection died."""
        conn = http.client.HTTPConnection(
            r.host, r.port, timeout=self.config.request_timeout_s)
        try:
            # explicit connect with its own (short) timeout so a dead
            # replica is classified BEFORE any request bytes move
            conn.sock = socket.create_connection(
                (r.host, r.port), timeout=self.config.connect_timeout_s)
            conn.sock.settimeout(self.config.request_timeout_s)
        except OSError as e:
            conn.close()
            return ("unadmitted", e)
        headers = {"Content-Type": "application/json"}
        if span and span.trace_id:
            headers[wire.TRACE_HEADER] = span.context.to_wire()
        try:
            conn.request("POST", path, body=wire.dumps(body),
                         headers=headers)
            resp = conn.getresponse()
        except (OSError, http.client.HTTPException) as e:
            conn.close()
            return ("lost", e)
        return ("conn", conn, resp)

    def _post_once(self, r: Replica, path: str, body: dict, span):
        """One POST attempt, read to the end of the body, classified:
        ``("response", status, body)`` — the replica answered; else the
        transport classifications of :meth:`_connect_and_post`."""
        out = self._connect_and_post(r, path, body, span)
        if out[0] != "conn":
            return out
        _, conn, resp = out
        try:
            try:
                raw = resp.read()
            except (OSError, http.client.HTTPException) as e:
                return ("lost", e)
            try:
                parsed = wire.loads(raw) if raw else {}
            except wire.WireError:
                parsed = {}
            return ("response", resp.status, parsed)
        finally:
            conn.close()

    # -- generate (streaming) --------------------------------------------
    def generate(self, prompt, *, max_new_tokens: Optional[int] = None,
                 priority: Optional[int] = None,
                 slo_class: Optional[str] = None,
                 deadline_s: Optional[float] = None) -> Iterator[int]:
        """Route one generation request and stream its tokens. The
        returned iterator yields ints as the replica emits them and ends
        with normal exhaustion on completion — or raises the typed
        terminal outcome AFTER the partial tokens (a replica that drains
        or dies mid-stream delivers what it produced, then the typed
        error; :class:`ReplicaLost` when the connection died). Dispatch
        and the unadmitted-retry decision happen eagerly in this call;
        consume the iterator to completion for exact accounting."""
        body: Dict[str, Any] = {
            "schema_version": wire.WIRE_SCHEMA_VERSION,
            "prompt": [int(t) for t in np.asarray(prompt).reshape(-1)],
        }
        if max_new_tokens is not None:
            body["max_new_tokens"] = int(max_new_tokens)
        if priority is not None:
            body["priority"] = int(priority)
        if slo_class is not None:
            body["slo_class"] = slo_class
        if deadline_s is not None:
            body["deadline_s"] = float(deadline_s)
        span = _trace.root_span("router.request", route="generate")
        self._note_submitted()
        t0 = time.monotonic()
        try:
            conn, resp, replica = self._open_stream(body, span)
        except BaseException as e:
            self._note_outcome(self._outcome_key(e),
                               getattr(e, "replica", ""))
            span.end(error=e)
            raise
        return self._stream_tokens(conn, resp, replica, span, t0)

    def _open_stream(self, body, span):
        """Dispatch /v1/generate with the same unadmitted-retry policy
        as submit, stopping at response HEADERS (the body streams).
        Routed only to replicas advertising the generative capability."""
        def attempt(r: Replica):
            out = self._connect_and_post(r, "/v1/generate", body, span)
            if out[0] != "conn":
                return out
            _, conn, resp = out
            if resp.status == 200:
                return ("final", lambda: (conn, resp, r))
            try:
                raw = resp.read()
            except (OSError, http.client.HTTPException):
                raw = b""
            conn.close()
            try:
                parsed = wire.loads(raw) if raw else {}
            except wire.WireError:
                parsed = {}

            def raise_typed(parsed=parsed, status=resp.status):
                raise wire.error_from_body(
                    parsed, f"replica {r.replica_id} status {status}")

            if wire.response_is_unadmitted(resp.status, parsed):
                return ("reject", resp.status, raise_typed)
            return ("final", raise_typed)

        return self._route_with_retry(attempt, generative=True)

    def _stream_tokens(self, conn, resp, replica: Replica,
                       span, t0: float) -> Iterator[int]:
        streamed = 0
        outcome_err: Optional[BaseException] = None
        done = False
        try:
            while True:
                try:
                    line = resp.readline()
                except (OSError, http.client.HTTPException) as e:
                    outcome_err = ReplicaLost(
                        f"fleet: replica {replica.replica_id} died "
                        f"mid-stream after {streamed} token(s): {e}",
                        replica=replica.replica_id)
                    break
                if not line:
                    if not done:
                        outcome_err = ReplicaLost(
                            f"fleet: replica {replica.replica_id} closed "
                            f"the stream without a terminal chunk "
                            f"({streamed} token(s) delivered)",
                            replica=replica.replica_id)
                    break
                try:
                    obj = wire.loads(line)
                except wire.WireError:
                    continue
                if obj.get("done"):
                    done = True
                    if obj.get("error"):
                        outcome_err = wire.error_from_body(obj)
                    break
                for t in obj.get("tokens", ()):
                    streamed += 1
                    yield int(t)
        finally:
            conn.close()
            if outcome_err is None and not done:
                # generator closed early by the caller: the replica-side
                # outcome still lands; fleet-wide this call is abandoned
                outcome_err = ReplicaLost(
                    f"fleet: generate stream abandoned by the caller "
                    f"after {streamed} token(s)",
                    replica=replica.replica_id)
            if outcome_err is not None:
                self._note_outcome(self._outcome_key(outcome_err),
                                   replica.replica_id)
                span.end(error=outcome_err)
            else:
                span.set_attribute("outcome", "completed")
                span.set_attribute("replica", replica.replica_id)
                span.end()
                self._note_outcome("completed", replica.replica_id)
                if _monitor.enabled():
                    _monitor.histogram(
                        "router_request_seconds",
                        "end-to-end fleet request latency through the "
                        "router (completed requests; p50/p99 in the "
                        "snapshot)").observe(time.monotonic() - t0)
            if _monitor.enabled() and streamed:
                _monitor.counter(
                    "fleet_stream_tokens_total",
                    "tokens delivered over streaming fleet responses"
                ).inc(streamed)
        if outcome_err is not None:
            raise outcome_err
