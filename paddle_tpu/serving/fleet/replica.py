"""Fleet replica worker: one engine + one front-end as a process.

``python -m paddle_tpu.serving.fleet.replica --model mlp_tiny --port 0``
builds a model probe, initializes parameters, warms up the bucket
executables (loading them from the warm-start cache when
``--aot-cache`` / ``FLAGS_aot_cache_dir`` points at one), starts the
HTTP front-end and the engine, installs the SIGTERM preemption handler,
and announces readiness as ONE JSON line on stdout::

    {"event": "ready", "replica_id": "r0", "port": 40913,
     "time_to_ready_s": 3.1, "warm_up_s": 1.4, "buckets": 4,
     "aot_cache": {"hits": 0, "misses": 4, "saves": 4, "errors": 0}}

``time_to_ready_s`` is measured from process entry (imports included —
what a fleet scheduler actually waits for); ``warm_up_s`` isolates the
compile storm the warm-start cache removes. The parent (the router's
supervisor, ``tools/load_check.py --fleet``) reads the line, registers
the replica, and later SIGTERMs it: the preemption handler drains the
engine (every admitted request still reaches its typed outcome),
``/readyz`` flips 503 so the router routes away, the front-end finishes
writing in-flight responses, and the process prints an ``exit`` event
with its final accounting and exits 0.
"""
from __future__ import annotations

import argparse
import json
import sys
import time


def build_probe(name: str, config):
    """(engine, feed_meta) for one of the named model probes. Feed
    construction stays in the wire's hands — the replica only needs the
    engine; ``feed_meta`` documents the expected feed for humans."""
    import paddle_tpu as fluid
    import paddle_tpu.unique_name as un
    from paddle_tpu import serving

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    if name == "mlp_tiny":
        from paddle_tpu.models.mlp import build_mnist_mlp

        with un.guard():
            net = build_mnist_mlp(hidden=(32,))
            infer = net["main"].clone(for_test=True)
        with fluid.scope_guard(scope):
            exe.run(net["startup"], scope=scope)
        eng = serving.ServingEngine(
            infer, feed_names=["img", "label"],
            fetch_list=[net["logits"].name], scope=scope, executor=exe,
            config=config)
        return eng, {"feeds": {"img": [784], "label": [1]}}
    if name == "resnet_tiny":
        from paddle_tpu.models.resnet import build_resnet

        with un.guard():
            net = build_resnet(depth=18, class_num=10,
                               image_shape=(3, 16, 16),
                               build_optimizer=False)
            infer = net["main"].clone(for_test=True)
        with fluid.scope_guard(scope):
            exe.run(net["startup"], scope=scope)
        eng = serving.ServingEngine(
            infer, feed_names=["img", "label"],
            fetch_list=[net["logits"].name], scope=scope, executor=exe,
            config=config)
        return eng, {"feeds": {"img": [3, 16, 16], "label": [1]}}
    if name == "gpt_tiny":
        from paddle_tpu.models.gpt import GptConfig, build_gpt_generative

        with un.guard():
            net = build_gpt_generative(GptConfig.tiny(), batch_slots=4,
                                       max_seq=32, page_size=8,
                                       prompt_buckets=(8, 16))
        with fluid.scope_guard(scope):
            exe.run(net["startup"], scope=scope)
        eng = serving.GenerativeEngine(
            net, scope=scope, executor=exe, config=config,
            gen_config=serving.GenerationConfig(decode_chunk=2))
        return eng, {"generative": True, "prompt_buckets": [8, 16]}
    raise SystemExit(f"unknown --model {name!r} "
                     f"(known: mlp_tiny, resnet_tiny, gpt_tiny)")


def main(argv=None) -> int:
    """Crash-safe entry: whatever kills the serve path, the ``exit``
    JSON event still ships on stdout (reason + best-effort final
    accounting) so the supervisor can CLASSIFY the failure from the
    event stream instead of guessing from the exit code alone. Only a
    real SIGKILL/`os._exit` (the ``kill`` fault action) leaves no event
    — which is itself the supervisor's 'kill' classification."""
    t_start = time.perf_counter()
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", default="mlp_tiny")
    ap.add_argument("--replica-id", default="r0")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--queue-depth", type=int, default=128)
    ap.add_argument("--queue-age-s", type=float, default=0.0)
    ap.add_argument("--batch-window-s", type=float, default=0.005)
    ap.add_argument("--aot-cache", default="",
                    help="warm-start executable cache dir "
                         "(sets FLAGS_aot_cache_dir)")
    ap.add_argument("--trace", action="store_true",
                    help="enable FLAGS_trace so request roots join the "
                         "router's trace ids")
    ap.add_argument("--set-flag", action="append", default=[],
                    metavar="FLAGS_name=value",
                    help="set any framework flag in this replica "
                         "(repeatable) — how the chaos gate arms "
                         "per-replica fault plans, bisection and "
                         "nan checks")
    ap.add_argument("--crash-after-s", type=float, default=0.0,
                    help="chaos hook: raise a RuntimeError this many "
                         "seconds after ready (a REAL crash through the "
                         "crash-path exit event) — the supervisor gate's "
                         "deterministic crashing replica. 0 disables")
    ap.add_argument("--linger-s", type=float, default=2.0,
                    help="keep the front-end answering for this long "
                         "after the drain completes (clean 410 "
                         "rejections a router retries on a sibling, "
                         "instead of connections dying in the accept "
                         "backlog at process exit)")
    args = ap.parse_args(argv)
    state: dict = {}
    try:
        return _serve(args, t_start, state)
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception as e:
        import traceback

        traceback.print_exc()
        info = {"event": "exit", "replica_id": args.replica_id,
                "reason": "crash", "error": f"{type(e).__name__}: {e}"}
        try:
            eng = state.get("engine")
            if eng is not None:
                info["accounting"] = eng.accounting()
        except Exception:
            pass
        print(json.dumps(info), flush=True)
        return 21


def _serve(args, t_start: float, state: dict) -> int:
    import paddle_tpu as fluid
    from paddle_tpu import aot_cache, serving
    from paddle_tpu.serving.fleet import ServingFrontend

    flags = {}
    if args.aot_cache:
        flags["FLAGS_aot_cache_dir"] = args.aot_cache
    if args.trace:
        flags["FLAGS_trace"] = 1
    for kv in args.set_flag:
        if "=" not in kv:
            raise SystemExit(f"--set-flag needs FLAGS_name=value, "
                             f"got {kv!r}")
        k, v = kv.split("=", 1)
        flags[k] = v
    if flags:
        fluid.set_flags(flags)

    config = serving.ServingConfig(
        max_batch=args.max_batch, queue_depth=args.queue_depth,
        queue_age_s=args.queue_age_s, batch_window_s=args.batch_window_s)
    eng, meta = build_probe(args.model, config)
    state["engine"] = eng

    t0 = time.perf_counter()
    buckets = eng.warm_up()
    warm_up_s = time.perf_counter() - t0
    cache = aot_cache.cache_stats()

    # fleet-shared autotuning visibility: how many warm-up compiles hit
    # the shared CostDatabase vs re-measured (the autoscale gate asserts
    # a scaled-out replica warms with hits >= 1 and zero re-trials)
    from paddle_tpu import monitor, tuning
    autotune = {"mode": tuning.autotune_mode(),
                "hits": int(monitor.metric_value(
                    "autotune_hits_total", 0.0)),
                "misses": int(monitor.metric_value(
                    "autotune_misses_total", 0.0)),
                "trials": int(monitor.metric_value(
                    "autotune_trials_total", 0.0))}

    startup = {"model": args.model, "warm_up_s": warm_up_s,
               "buckets": buckets, "aot_cache": cache,
               "autotune": autotune,
               "time_to_ready_s": time.perf_counter() - t_start}
    frontend = ServingFrontend(eng, host=args.host, port=args.port,
                               replica_id=args.replica_id,
                               extra_health=startup)
    port = frontend.start()
    eng.start()
    eng.install_preemption_handler()
    startup["time_to_ready_s"] = time.perf_counter() - t_start
    # the front-end holds its own copy of extra_health: refresh it so
    # /healthz's "startup" agrees with the ready event below
    frontend.extra_health.update(startup)

    print(json.dumps({"event": "ready", "replica_id": args.replica_id,
                      "model": args.model, "port": port, **startup}),
          flush=True)

    # serve until the preemption handler (SIGTERM / request_shutdown)
    # drain-stops the engine; stop() runs on the graceful callback
    # thread and returns only after the dispatch thread exits, so
    # "stopped and dispatch thread dead" == drain complete
    crash_at = (time.monotonic() + args.crash_after_s
                if args.crash_after_s > 0 else None)
    try:
        while True:
            time.sleep(0.1)
            if crash_at is not None and time.monotonic() >= crash_at:
                # the chaos hook: a genuine exception through the
                # crash-path handler, exit event included
                raise RuntimeError(
                    f"injected replica crash (--crash-after-s "
                    f"{args.crash_after_s:g})")
            if eng._stopped and (eng._thread is None
                                 or not eng._thread.is_alive()):
                break
    except KeyboardInterrupt:
        eng.stop(drain=True)

    # drain complete — but a router whose pressure snapshot predates the
    # drain may still be dispatching here. Linger with the front-end up:
    # those dispatches meet a clean 410 (admitted=false, safely retried
    # on a sibling) instead of a connection that dies in the accept
    # backlog when this process exits — which the router must settle as
    # ReplicaLost (possibly admitted, never retryable).
    if args.linger_s > 0:
        time.sleep(args.linger_s)

    acct = eng.accounting()
    frontend.stop(wait_inflight_s=10.0)
    print(json.dumps({"event": "exit", "replica_id": args.replica_id,
                      "reason": "drain", "accounting": acct}),
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
