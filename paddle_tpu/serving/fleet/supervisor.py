"""Replica supervisor: the fleet's self-healing process manager.

Until now a crashed replica stayed dead forever — the only "supervisor"
was the CI gate (`tools/load_check.py --fleet`). This module owns
replica subprocesses end-to-end, the way the ROADMAP's
millions-of-users deployment (and the cross-replica sharding paper's
operating assumption: preemption/restart is ROUTINE) requires:

* **spawn** — ``python -m paddle_tpu.serving.fleet.replica`` per
  replica, stderr appended to one log per replica id across restarts,
  stdout event stream parsed live;
* **ready** — the replica's ``ready`` JSON event registers it with the
  :class:`~.router.FleetRouter` (``add_replica`` first time,
  ``reassign_replica`` on restart — same id, NEW port) and triggers one
  ``poll_now()`` so a restarted replica is fresh capacity within one
  poll. Restarts come up warm through the shared AOT executable cache
  (``--aot-cache``);
* **exit classification** — from the replica's ``exit`` event when one
  exists (the crash path emits it too), else from the exit code:
  ``drain`` (supervisor-requested or SIGTERM-graceful, never
  restarted when requested), ``crash`` (exit event with
  ``reason=crash`` or an unexpected nonzero exit), ``kill`` (SIGKILL /
  ``os._exit`` — no exit event, signal-style return code),
  ``ready_timeout`` (never became ready);
* **restart with backoff** — exponential + seeded jitter via the SAME
  :class:`~paddle_tpu.resilience.retry.RetryPolicy` the transient-site
  retries use (``supervisor_restarts_total{reason}``);
* **crash-loop breaker** — more than ``max_restarts`` restarts inside
  ``restart_window_s`` RETIRES the replica with a typed
  :class:`ReplicaCrashLoop` (stored on the handle, raised by
  :meth:`ReplicaSupervisor.check`, removed from the router) — never a
  silent restart spin.

``tools/load_check.py --fleet-chaos`` is the CI gate: a crashed replica
must be restarted within its backoff budget and serve again, and a
forced crash-loop must retire typed. docs/SERVING.md "Fleet
self-healing" has the state machine.
"""
from __future__ import annotations

import dataclasses
import json
import logging
import os
import random
import signal
import subprocess
import sys
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence

from ... import monitor as _monitor
from ...resilience.retry import RetryPolicy
from ..engine import ServingError
from .router import FleetRouter, Replica

__all__ = ["ReplicaSupervisor", "SupervisorConfig", "SupervisedReplica",
           "ReplicaCrashLoop"]

logger = logging.getLogger("paddle_tpu.serving.fleet")


class ReplicaCrashLoop(ServingError):
    """A replica restarted ``restarts`` times inside ``window_s`` seconds
    and was RETIRED: restarting a deterministically-crashing replica any
    further is an outage amplifier, not healing. Typed and stored on the
    replica's handle (``handle.error``); :meth:`ReplicaSupervisor.check`
    raises it."""

    def __init__(self, msg: str, replica: str = "", restarts: int = 0,
                 window_s: float = 0.0):
        self.replica = replica
        self.restarts = restarts
        self.window_s = window_s
        super().__init__(msg)


@dataclasses.dataclass
class SupervisorConfig:
    """Supervision knobs. ``restart=False`` is the chaos gate's negative
    control: spawn once, never heal — the gate must provably fail."""

    max_restarts: int = 3          # restarts inside restart_window_s ...
    restart_window_s: float = 60.0  # ... before the crash-loop retire
    backoff_base_s: float = 0.25   # exponential restart backoff (seeded
    backoff_max_s: float = 5.0     # jitter via resilience RetryPolicy)
    ready_timeout_s: float = 240.0  # spawn -> ready bound (cold compile)
    exit_grace_s: float = 30.0     # SIGTERM drain wait before SIGKILL
    seed: int = 0
    restart: bool = True
    # fleet-shared flags, passed to EVERY spawned replica as
    # ``--set-flag name=value`` pairs: how one autotune CostDatabase
    # (FLAGS_autotune_db — flock-merge safe) and one AOT cache warm the
    # whole fleet, so a scale-out replica compiles straight to
    # best-known configs instead of re-measuring
    shared_flags: Optional[Dict[str, str]] = None


class SupervisedReplica:
    """One supervised replica's live state (thread-safe reads; the
    supervisor's monitor thread writes). ``state``: ``spawning`` ->
    ``ready`` -> (``backoff`` -> ``spawning``)* -> ``retired`` |
    ``stopped`` | ``down``."""

    def __init__(self, replica_id: str, model: str, aot_dir: str,
                 extra_args: Sequence[str],
                 initial_extra_args: Sequence[str], host: str):
        self.replica_id = replica_id
        self.model = model
        self.aot_dir = aot_dir
        self.extra_args = list(extra_args)
        self.initial_extra_args = list(initial_extra_args)
        self.host = host
        self.state = "spawning"
        self.proc: Optional[subprocess.Popen] = None
        self.port: Optional[int] = None
        self.spawns = 0                 # completed spawn attempts
        self.restarts = 0               # restarts performed (total)
        self.restart_times: List[float] = []   # monotonic, window-pruned
        self.last_exit: Optional[dict] = None  # {"rc", "reason", ...}
        self.ready_info: Optional[dict] = None
        self.exit_info: Optional[dict] = None  # last parsed exit event
        self.error: Optional[ReplicaCrashLoop] = None
        self.events: List[tuple] = []   # (monotonic, kind, detail) audit
        self.stop_requested = False
        self.drain_requested = False
        self._ready_ev = threading.Event()
        self._retired_ev = threading.Event()
        self.thread: Optional[threading.Thread] = None

    def note(self, kind: str, detail: str = "") -> None:
        self.events.append((time.monotonic(), kind, detail))
        logger.info("supervisor[%s]: %s %s", self.replica_id, kind, detail)

    def wait_ready(self, timeout: Optional[float] = None) -> dict:
        """Block until the replica is ready AND registered with the
        router (``state == "ready"``). A retired replica raises its
        typed :class:`ReplicaCrashLoop` immediately — never a silent
        wait on a replica that will not come."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            if self.error is not None:
                raise self.error          # retired: fail fast, typed
            if self._ready_ev.is_set() and self.state == "ready":
                return dict(self.ready_info or {})
            if self.state in ("down", "stopped"):
                # spawn-once mode after a crash, or a requested stop:
                # no further incarnation is coming — never a silent wait
                raise RuntimeError(
                    f"supervisor: replica {self.replica_id} is "
                    f"{self.state} and will not become ready "
                    f"(last exit: {self.last_exit})")
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"supervisor: replica {self.replica_id} not ready "
                    f"within {timeout:g}s (state={self.state})")
            time.sleep(0.02)

    def wait_retired(self, timeout: Optional[float] = None) -> bool:
        return self._retired_ev.wait(timeout)

    def status(self) -> dict:
        return {"replica_id": self.replica_id, "state": self.state,
                "port": self.port, "spawns": self.spawns,
                "restarts": self.restarts,
                "last_exit": self.last_exit,
                "error": str(self.error) if self.error else None}


class ReplicaSupervisor:
    """See module docstring. ``router=None`` supervises processes without
    routing (tests); ``spawn_command`` overrides the argv builder (tests
    substitute a lightweight stub for the real replica module)."""

    def __init__(self, router: Optional[FleetRouter] = None,
                 config: Optional[SupervisorConfig] = None,
                 log_dir: str = ".",
                 env: Optional[Dict[str, str]] = None,
                 cwd: Optional[str] = None,
                 spawn_command: Optional[
                     Callable[["SupervisedReplica"], List[str]]] = None):
        self.router = router
        self.config = config or SupervisorConfig()
        self.log_dir = log_dir
        self.env = env
        self.cwd = cwd
        self._spawn_command = spawn_command or self._default_command
        self._lock = _monitor.make_lock("ReplicaSupervisor._lock")
        self._stop_ev = threading.Event()
        self.replicas: Dict[str, SupervisedReplica] = {}
        self._aggregator = None   # telemetry plane, see start_telemetry

    # -- public surface --------------------------------------------------
    def add_replica(self, replica_id: str, model: str = "mlp_tiny",
                    aot_dir: str = "", extra_args: Sequence[str] = (),
                    initial_extra_args: Sequence[str] = (),
                    host: str = "127.0.0.1") -> SupervisedReplica:
        """Start supervising one replica. ``extra_args`` ride EVERY
        spawn; ``initial_extra_args`` only the first (how the gate makes
        a replica that crashes once and comes back healthy)."""
        with self._lock:
            if replica_id in self.replicas:
                raise ValueError(f"supervisor: replica id '{replica_id}' "
                                 f"already supervised")
            h = SupervisedReplica(replica_id, model, aot_dir, extra_args,
                                  initial_extra_args, host)
            self.replicas[replica_id] = h
        h.thread = threading.Thread(
            target=self._supervise, args=(h,),
            name=f"paddle_tpu-supervisor-{replica_id}", daemon=True)
        h.thread.start()
        self._gauge_live()
        return h

    def handle(self, replica_id: str) -> SupervisedReplica:
        with self._lock:   # add_replica resizes the dict concurrently
            return self.replicas[replica_id]

    def drain(self, replica_id: str) -> None:
        """Graceful SIGTERM drain of one replica; the supervisor will
        NOT restart it."""
        h = self.handle(replica_id)
        h.drain_requested = True
        h.stop_requested = True
        self._signal(h, signal.SIGTERM)

    def kill(self, replica_id: str) -> None:
        """Chaos helper: SIGKILL the replica process WITHOUT telling the
        supervisor — exactly what an OOM kill or host loss looks like,
        so the restart path is exercised for real."""
        h = self.handle(replica_id)
        if h.proc is not None and h.proc.poll() is None:
            h.proc.kill()

    def _handles(self) -> List[SupervisedReplica]:
        """Snapshot for lock-free iteration (add_replica mutates the
        dict under ``_lock``; iterating it live could tear)."""
        with self._lock:
            return list(self.replicas.values())

    def check(self) -> None:
        """Raise the first typed :class:`ReplicaCrashLoop` any replica
        retired with (the 'never a silent spin' contract)."""
        for h in self._handles():
            if h.error is not None:
                raise h.error

    def status(self) -> Dict[str, dict]:
        return {h.replica_id: h.status() for h in self._handles()}

    def start_telemetry(self, config=None):
        """Attach a :class:`~.telemetry.FleetAggregator` scraping this
        supervisor's router membership (restarted replicas are picked
        up within one scrape, exactly like the routing poll). Returns
        the aggregator, or ``None`` without a router or while
        ``FLAGS_fleet_telemetry`` is off (the disabled plane spawns no
        thread)."""
        from . import telemetry

        if self.router is None or not telemetry.enabled():
            return None
        if self._aggregator is None:
            self._aggregator = telemetry.FleetAggregator.for_router(
                self.router, config)
            self._aggregator.start()
        return self._aggregator

    def stop(self, drain: bool = True) -> None:
        """Stop supervising: no further restarts; drain (or kill) every
        live replica and join the monitor threads."""
        self._stop_ev.set()
        agg, self._aggregator = self._aggregator, None
        if agg is not None:
            agg.stop()
        handles = self._handles()
        for h in handles:
            h.stop_requested = True
            if drain:
                h.drain_requested = True
                self._signal(h, signal.SIGTERM)
            elif h.proc is not None and h.proc.poll() is None:
                h.proc.kill()
        deadline = time.monotonic() + self.config.exit_grace_s
        for h in handles:
            if h.thread is not None:
                h.thread.join(max(0.1, deadline - time.monotonic()))
        for h in handles:
            if h.proc is not None and h.proc.poll() is None:
                logger.warning("supervisor: replica %s did not drain in "
                               "%gs — SIGKILL", h.replica_id,
                               self.config.exit_grace_s)
                h.proc.kill()
            if h.thread is not None:
                h.thread.join(10.0)
        self._gauge_live()

    def __enter__(self) -> "ReplicaSupervisor":
        return self

    def __exit__(self, *exc) -> bool:
        self.stop(drain=True)
        return False

    # -- spawning --------------------------------------------------------
    def _default_command(self, h: SupervisedReplica) -> List[str]:
        cmd = [sys.executable, "-m", "paddle_tpu.serving.fleet.replica",
               "--model", h.model, "--replica-id", h.replica_id,
               "--host", h.host, "--port", "0"]
        if h.aot_dir:
            cmd += ["--aot-cache", h.aot_dir]
        # fleet-shared flags ride every spawn, BEFORE the per-replica
        # extras so a replica-specific --set-flag can still override
        for name in sorted(self.config.shared_flags or {}):
            cmd += ["--set-flag",
                    f"{name}={self.config.shared_flags[name]}"]
        cmd += h.extra_args
        if h.spawns == 0:
            cmd += h.initial_extra_args
        return cmd

    def _spawn(self, h: SupervisedReplica) -> subprocess.Popen:
        cmd = self._spawn_command(h)
        os.makedirs(self.log_dir or ".", exist_ok=True)
        log_path = os.path.join(self.log_dir,
                                f"replica_{h.replica_id}.log")
        # append across restarts: one log tells the whole lifecycle story
        log = open(log_path, "a")
        try:
            proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                    stderr=log, text=True, env=self.env,
                                    cwd=self.cwd)
        finally:
            log.close()   # the child holds its own fd now
        h.spawns += 1
        h.proc = proc
        h.ready_info = None
        h.exit_info = None
        h._ready_ev.clear()
        h.note("spawn", f"pid {proc.pid} (spawn #{h.spawns})")
        threading.Thread(target=self._read_events, args=(h, proc),
                         daemon=True).start()
        return proc

    def _read_events(self, h: SupervisedReplica,
                     proc: subprocess.Popen) -> None:
        try:
            for line in proc.stdout:
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if obj.get("event") == "ready" and proc is h.proc:
                    h.ready_info = obj
                    h._ready_ev.set()
                elif obj.get("event") == "exit" and proc is h.proc:
                    h.exit_info = obj
        except Exception:                      # pragma: no cover
            pass

    # -- the per-replica supervision loop --------------------------------
    def _supervise(self, h: SupervisedReplica) -> None:
        """Crash-guarded shell: a supervisor bug (unspawnable command,
        unwritable log dir) must surface as a typed retired replica, not
        a silently dead daemon thread with callers stuck in
        ``wait_ready``."""
        try:
            self._supervise_inner(h)
        except Exception as e:
            logger.exception("supervisor: monitor thread for %s DIED",
                             h.replica_id)
            h.error = ReplicaCrashLoop(
                f"supervisor: monitor thread for {h.replica_id} died: "
                f"{type(e).__name__}: {e}", replica=h.replica_id)
            h.state = "retired"
            self._deregister(h)
            h._retired_ev.set()
            self._gauge_live()

    def _supervise_inner(self, h: SupervisedReplica) -> None:
        cfg = self.config
        rng = random.Random((int(cfg.seed) << 16)
                            ^ zlib.crc32(h.replica_id.encode()))
        policy = RetryPolicy(max_attempts=1_000_000,
                             base_delay=cfg.backoff_base_s,
                             max_delay=cfg.backoff_max_s,
                             multiplier=2.0, jitter=0.25, timeout=None)
        while True:
            if h.stop_requested or self._stop_ev.is_set():
                # a drain/stop that landed during the backoff must not
                # cost one more full spawn the caller asked never to run
                h.state = "stopped"
                self._deregister(h)
                self._gauge_live()
                return
            h.state = "spawning"
            proc = self._spawn(h)
            reason = self._run_one_incarnation(h, proc)
            h.last_exit = {"rc": proc.returncode, "reason": reason,
                           "exit_event": h.exit_info}
            h.note("exit", f"rc={proc.returncode} reason={reason}")
            if h.stop_requested or self._stop_ev.is_set():
                h.state = "stopped"
                self._deregister(h)
                self._gauge_live()
                return
            if not cfg.restart:
                # negative-control / spawn-once mode: the replica stays
                # down — loudly, with the classification on record
                h.state = "down"
                self._deregister(h)
                self._gauge_live()
                logger.error("supervisor: replica %s is DOWN (%s) and "
                             "restarts are disabled", h.replica_id, reason)
                return
            # crash-loop breaker BEFORE the restart: N restarts inside
            # the window retire the replica typed, never a silent spin
            now = time.monotonic()
            h.restart_times = [t for t in h.restart_times
                               if now - t < cfg.restart_window_s]
            if len(h.restart_times) >= cfg.max_restarts:
                h.error = ReplicaCrashLoop(
                    f"supervisor: replica {h.replica_id} crash-looped — "
                    f"{len(h.restart_times)} restart(s) inside "
                    f"{cfg.restart_window_s:g}s (last exit: {reason}, "
                    f"rc={proc.returncode}); RETIRED",
                    replica=h.replica_id,
                    restarts=len(h.restart_times),
                    window_s=cfg.restart_window_s)
                h.state = "retired"
                self._deregister(h)
                h._retired_ev.set()
                self._gauge_live()
                if _monitor.enabled():
                    _monitor.counter(
                        "supervisor_crash_loops_total",
                        "replicas retired by the crash-loop breaker"
                    ).labels(replica=h.replica_id).inc()
                logger.error("%s", h.error)
                return
            h.restart_times.append(now)
            h.restarts += 1
            delay = policy.delay(len(h.restart_times), rng)
            if _monitor.enabled():
                _monitor.counter(
                    "supervisor_restarts_total",
                    "replica restarts performed by the supervisor, by "
                    "exit classification").labels(reason=reason).inc()
            h.state = "backoff"
            h.note("restart", f"#{h.restarts} after {reason}, backoff "
                              f"{delay:.2f}s")
            # sliced wait: a per-replica drain() (no global event) must
            # also cut the backoff short; the loop top then exits with
            # the dead incarnation deregistered
            end = time.monotonic() + delay
            while time.monotonic() < end and not h.stop_requested:
                if self._stop_ev.wait(min(0.05,
                                          max(0.0,
                                              end - time.monotonic()))):
                    break

    def _run_one_incarnation(self, h: SupervisedReplica,
                             proc: subprocess.Popen) -> str:
        """Wait for ready (register) then exit; returns the exit
        classification: ``drain`` / ``crash`` / ``kill`` /
        ``ready_timeout``."""
        cfg = self.config
        deadline = time.monotonic() + cfg.ready_timeout_s
        while True:
            if h._ready_ev.wait(0.05):
                break
            if proc.poll() is not None:
                return self._classify_exit(h, proc)
            if time.monotonic() > deadline:
                logger.error("supervisor: replica %s not ready within "
                             "%gs — killing the spawn", h.replica_id,
                             cfg.ready_timeout_s)
                proc.kill()
                self._wait(proc, 10.0)
                return "ready_timeout"
            if h.stop_requested or self._stop_ev.is_set():
                # stop arrived while this incarnation was still coming
                # up: it may never have been signalled — do it here
                self._signal(h, signal.SIGTERM)
                if self._wait(proc, cfg.exit_grace_s) is None:
                    proc.kill()
                    self._wait(proc, 10.0)
                return self._classify_exit(h, proc)
        # ready: register as (fresh) capacity — within one poll. The
        # registration happens BEFORE the state flips to "ready", so
        # wait_ready() implies "routable through the router too".
        h.port = int(h.ready_info["port"])
        if self.router is not None:
            self.router.reassign_replica(h.replica_id, h.host, h.port)
            self.router.poll_now()
        h.state = "ready"
        h.note("ready", f"port {h.port} time_to_ready_s="
                        f"{h.ready_info.get('time_to_ready_s')}")
        proc.wait()
        # give the event-reader thread a beat to parse a final exit event
        for _ in range(20):
            if h.exit_info is not None:
                break
            time.sleep(0.05)
        return self._classify_exit(h, proc)

    @staticmethod
    def _classify_exit(h: SupervisedReplica,
                       proc: subprocess.Popen) -> str:
        rc = proc.returncode
        ev = h.exit_info or {}
        if ev.get("reason") == "drain" and rc == 0:
            return "drain"
        if ev.get("reason") == "crash":
            return "crash"
        if rc is not None and (rc < 0 or rc in (137, 124)):
            # signal-style death without an exit event: SIGKILL/OOM or
            # the 'kill' fault action's os._exit(137)
            return "kill"
        if rc == 0:
            return "drain"
        return "crash"

    @staticmethod
    def _wait(proc: subprocess.Popen,
              timeout: float) -> Optional[int]:
        """``Popen.wait`` that returns ``None`` on timeout instead of
        raising."""
        try:
            return proc.wait(timeout)
        except subprocess.TimeoutExpired:
            return None

    def _deregister(self, h: SupervisedReplica) -> None:
        if self.router is not None:
            self.router.remove_replica(h.replica_id)

    def _signal(self, h: SupervisedReplica, sig) -> None:
        if h.proc is not None and h.proc.poll() is None:
            try:
                h.proc.send_signal(sig)
            except OSError:                    # pragma: no cover
                pass

    def _gauge_live(self) -> None:
        if _monitor.enabled():
            _monitor.gauge(
                "supervisor_replicas_live",
                "supervised replicas currently spawning/ready/backoff"
            ).set(sum(1 for x in self._handles()
                      if x.state in ("spawning", "ready", "backoff")))
