"""Fleet autoscaler: the control loop that closes ROADMAP item 5
(docs/SERVING.md "Fleet control loop").

Every sensor and actuator already existed — this module connects them.
:class:`FleetAutoscaler` reads three sensor families each tick:

* the router's per-replica **pressure snapshots** (``queue_depth`` /
  ``degraded`` / ``open_buckets``, polled by ``FleetRouter``),
* the per-class **SLO burn state** (``ok`` / ``warning`` / ``burning``
  from the engines' ``SloBurnTracker``, via the FleetAggregator's
  counter-reset-aware fleet rollup when one is attached, else the
  router's health-poll worst-state), and
* the **supervisor's replica states** (spawning / ready / backoff),

and drives exactly two actuators: ``ReplicaSupervisor.add_replica``
(scale-out, warm through the fleet-shared AOT cache + autotune
CostDatabase carried by ``SupervisorConfig.shared_flags``) and
``ReplicaSupervisor.drain`` (scale-in, strictly the graceful-preemption
path: the victim flips ready-false, finishes everything admitted, exits
0, and the fleet ledger stays ``exact`` throughout).

**A decision is never silent.** Every tick ends in an act, a typed
refusal (``at_max_replicas`` / ``at_min_replicas`` / ``cooldown`` /
``spawn_budget_spent``) or a hold, and acts/refusals are metered
(``autoscaler_decisions_total{action,reason}``) and appended to a
bounded audit trail (consecutive repeats coalesce with a count — a
10-minute cooldown does not scroll 600 identical lines).

**The loop cannot flap.** Scale-out needs the hot signal sustained for
``hot_sustain_s``; scale-in needs calm sustained for ``calm_sustain_s``;
any act starts a ``cooldown_s`` window refusing further acts; and an
in-flight drain refuses concurrent scale decisions (typed ``cooldown``)
until the victim is fully retired. The clock is injectable
(``_now``, the ``SloBurnTracker`` idiom) so the hysteresis is
regression-testable without sleeping.

Lock discipline: the autoscaler lock is a leaf — it is never held
across a supervisor, router or aggregator call (those have their own
locks; holding ours across theirs would order-invert against the poll
threads). ``tick()`` is serialized by a dedicated tick lock so a
background loop and a manual tick cannot double-actuate.
"""
from __future__ import annotations

import dataclasses
import logging
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

from ... import monitor as _monitor
from ...flags import flag as _flag

__all__ = ["AutoscalerConfig", "FleetAutoscaler"]

logger = logging.getLogger("paddle_tpu.serving.fleet.autoscaler")

# supervisor states that count toward the replica budget (a replica in
# backoff is still owned capacity — it will come back or retire typed)
_LIVE_STATES = ("spawning", "ready", "backoff")

# typed refusal reasons (the only reasons a wanted act does not happen)
REFUSALS = ("at_max_replicas", "at_min_replicas", "cooldown",
            "spawn_budget_spent")


def _flag_default(value, name):
    return _flag(name) if value is None else value


@dataclasses.dataclass
class AutoscalerConfig:
    """Control-loop knobs. ``None`` fields resolve from the
    ``FLAGS_serving_autoscale_*`` family (docs/SERVING.md flag table),
    mirroring ``ServingConfig``."""

    min_replicas: Optional[int] = None
    max_replicas: Optional[int] = None
    interval_s: Optional[float] = None
    cooldown_s: Optional[float] = None
    hot_sustain_s: Optional[float] = None
    calm_sustain_s: Optional[float] = None
    max_inflight_spawns: Optional[int] = None
    queue_high: Optional[int] = None

    def resolve(self) -> "AutoscalerConfig":
        r = AutoscalerConfig(
            min_replicas=int(_flag_default(
                self.min_replicas, "serving_autoscale_min_replicas")),
            max_replicas=int(_flag_default(
                self.max_replicas, "serving_autoscale_max_replicas")),
            interval_s=float(_flag_default(
                self.interval_s, "serving_autoscale_interval_s")),
            cooldown_s=float(_flag_default(
                self.cooldown_s, "serving_autoscale_cooldown_s")),
            hot_sustain_s=float(_flag_default(
                self.hot_sustain_s, "serving_autoscale_hot_sustain_s")),
            calm_sustain_s=float(_flag_default(
                self.calm_sustain_s, "serving_autoscale_calm_sustain_s")),
            max_inflight_spawns=int(_flag_default(
                self.max_inflight_spawns,
                "serving_autoscale_max_inflight_spawns")),
            queue_high=int(_flag_default(
                self.queue_high, "serving_autoscale_queue_high")),
        )
        if r.min_replicas < 0:
            raise ValueError(f"autoscaler: min_replicas must be >= 0, "
                             f"got {r.min_replicas}")
        if r.max_replicas < max(1, r.min_replicas):
            raise ValueError(
                f"autoscaler: max_replicas must be >= "
                f"max(1, min_replicas), got {r.max_replicas} with "
                f"min_replicas {r.min_replicas}")
        if r.max_inflight_spawns < 1:
            raise ValueError(f"autoscaler: max_inflight_spawns must be "
                             f">= 1, got {r.max_inflight_spawns}")
        return r


class FleetAutoscaler:
    """See module docstring. ``supervisor`` needs ``add_replica`` /
    ``drain`` / ``status()`` (duck-typed — tests substitute fakes);
    ``router`` defaults to the supervisor's; ``aggregator`` (optional)
    upgrades the burn sensor from the router's worst-state to the
    fleet-rollup per-class view. ``model`` / ``aot_dir`` /
    ``extra_args`` template every scale-out spawn."""

    def __init__(self, supervisor, router=None, aggregator=None,
                 config: Optional[AutoscalerConfig] = None, *,
                 model: str = "mlp_tiny", aot_dir: str = "",
                 extra_args: Sequence[str] = (),
                 replica_id_prefix: str = "as",
                 _now=time.monotonic):
        self.supervisor = supervisor
        self.router = router if router is not None \
            else getattr(supervisor, "router", None)
        self.aggregator = aggregator
        self.config = (config or AutoscalerConfig()).resolve()
        self.model = model
        self.aot_dir = aot_dir
        self.extra_args = list(extra_args)
        self.replica_id_prefix = replica_id_prefix
        self._now = _now

        # serializes tick(); never acquired by readers
        self._tick_lock = _monitor.make_lock("FleetAutoscaler._tick_lock")
        # leaf lock for the state below — NEVER held across a
        # supervisor/router/aggregator call
        self._lock = _monitor.make_lock("FleetAutoscaler._lock")
        self._hot_since: Optional[float] = None
        self._calm_since: Optional[float] = None
        self._last_action_t: Optional[float] = None
        self._spawned: List[str] = []       # autoscaler-spawned, LIFO
        self._draining: Dict[str, float] = {}   # victim -> drain start
        self._seq = 0
        self._audit: deque = deque(maxlen=256)
        self._last_decision: Optional[dict] = None
        self._last_sense: Dict[str, Any] = {}

        self._stop_ev = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- control loop ----------------------------------------------------
    def start(self) -> "FleetAutoscaler":
        """Spawn the background tick thread (``interval_s`` cadence).
        Tests usually skip this and drive :meth:`tick` directly."""
        if self._thread is not None:
            return self
        self._stop_ev.clear()
        self._thread = threading.Thread(
            target=self._loop, name="paddle_tpu-fleet-autoscaler",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_ev.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(10.0)

    def _loop(self) -> None:
        while not self._stop_ev.wait(self.config.interval_s):
            try:
                self.tick()
            except Exception:
                # the control loop must outlive a torn sensor read (a
                # replica dying mid-scrape); the failure is logged, the
                # next tick re-senses from scratch
                logger.exception("autoscaler: tick failed; continuing")

    def tick(self) -> dict:
        """One full sense -> decide -> act cycle. Returns the decision
        record (also kept as ``status()['last_decision']`` when it is an
        act or a refusal)."""
        with self._tick_lock:
            now = self._now()
            sense = self._sense(now)
            decision = self._decide(sense, now)
            self._publish_gauges(sense)
            return decision

    # -- sensors ---------------------------------------------------------
    def _sense(self, now: float) -> Dict[str, Any]:
        status = self.supervisor.status()
        live = [rid for rid, st in status.items()
                if st.get("state") in _LIVE_STATES]
        spawning = [rid for rid, st in status.items()
                    if st.get("state") == "spawning"]
        # prune drains whose victim fully retired (state left the live
        # set): only then may the cooldown-by-drain release
        with self._lock:
            for rid in list(self._draining):
                if status.get(rid, {}).get("state") not in _LIVE_STATES:
                    del self._draining[rid]
            draining = list(self._draining)

        pressure, press_why = self._sense_pressure()
        burn, classes = self._sense_burn()
        hot = pressure or burn
        with self._lock:
            if hot:
                if self._hot_since is None:
                    self._hot_since = now
                self._calm_since = None
            else:
                if self._calm_since is None:
                    self._calm_since = now
                self._hot_since = None
            sense = {
                "replicas": len(live), "live": sorted(live),
                "spawning": len(spawning), "draining": draining,
                "pressure": pressure, "pressure_why": press_why,
                "burning": burn, "slo_classes": classes, "hot": hot,
                "hot_for_s": (now - self._hot_since
                              if self._hot_since is not None else 0.0),
                "calm_for_s": (now - self._calm_since
                               if self._calm_since is not None else 0.0),
            }
            self._last_sense = sense
        return sense

    def _sense_pressure(self):
        """True when any polled-ready replica shows admission pressure:
        deep queue, degraded mode, or open breaker buckets."""
        if self.router is None:
            return False, ""
        for rep in list(self.router.replicas):
            snap = rep.snapshot()
            if not snap.get("ready"):
                continue
            if snap.get("queue_depth", 0) >= self.config.queue_high:
                return True, (f"{rep.replica_id}: queue_depth "
                              f"{snap['queue_depth']} >= "
                              f"{self.config.queue_high}")
            if snap.get("degraded"):
                return True, f"{rep.replica_id}: degraded"
            if snap.get("open_buckets", 0) > 0:
                return True, (f"{rep.replica_id}: "
                              f"{snap['open_buckets']} open buckets")
        return False, ""

    def _sense_burn(self):
        """(any class burning?, per-class worst state map). Prefers the
        aggregator's exact fleet rollup; falls back to the router
        health-poll's per-replica worst state."""
        classes: Dict[str, str] = {}
        if self.aggregator is not None:
            snap = self.aggregator.snapshot()
            for rec in snap.get("replicas", {}).values():
                for name, cls in ((rec.get("slo") or {}).get("classes")
                                  or {}).items():
                    classes[name] = _worst(classes.get(name),
                                           cls.get("state"))
            if not classes:
                state = snap.get("fleet", {}).get("slo_state")
                if state:
                    classes["_fleet"] = state
        elif self.router is not None:
            for rep in list(self.router.replicas):
                state = rep.snapshot().get("slo_state")
                if state and state != "unknown":
                    classes["_fleet"] = _worst(classes.get("_fleet"),
                                               state)
        return any(s == "burning" for s in classes.values()), classes

    # -- decisions -------------------------------------------------------
    def _decide(self, sense: Dict[str, Any], now: float) -> dict:
        cfg = self.config
        if sense["hot"] and sense["hot_for_s"] >= cfg.hot_sustain_s:
            why = ("slo_burn" if sense["burning"]
                   else f"pressure ({sense['pressure_why']})")
            if sense["replicas"] >= cfg.max_replicas:
                return self._record("refuse_scale_out", "at_max_replicas",
                                    f"{sense['replicas']} replicas >= "
                                    f"max {cfg.max_replicas}; hot: {why}",
                                    now)
            if sense["spawning"] >= cfg.max_inflight_spawns:
                return self._record(
                    "refuse_scale_out", "spawn_budget_spent",
                    f"{sense['spawning']} spawns in flight >= "
                    f"{cfg.max_inflight_spawns}; hot: {why}", now)
            refused = self._cooldown_refusal(now)
            if refused:
                return self._record("refuse_scale_out", "cooldown",
                                    f"{refused}; hot: {why}", now)
            return self._scale_out(why, now)
        if (not sense["hot"]
                and sense["calm_for_s"] >= cfg.calm_sustain_s):
            if sense["replicas"] <= cfg.min_replicas:
                # steady state at the floor: holding there forever is
                # the expected calm condition, not a refusal storm worth
                # an audit line per tick — metered, deduped in the audit
                return self._record("refuse_scale_in", "at_min_replicas",
                                    f"{sense['replicas']} replicas <= "
                                    f"min {cfg.min_replicas}", now)
            refused = self._cooldown_refusal(now)
            if refused:
                return self._record("refuse_scale_in", "cooldown",
                                    refused, now)
            victim = self._pick_victim(sense)
            if victim is None:
                return self._record("refuse_scale_in", "at_min_replicas",
                                    "no drainable victim", now)
            return self._scale_in(victim, now)
        return {"action": "hold", "reason": "steady",
                "detail": (f"hot_for {sense['hot_for_s']:.1f}s / "
                           f"calm_for {sense['calm_for_s']:.1f}s"),
                "t": now}

    def _cooldown_refusal(self, now: float) -> str:
        """Non-empty reason string when an act must be refused typed
        ``cooldown``: inside the post-act window, or a drain in flight
        (scale decisions during a drain are exactly the race the
        regression test pins)."""
        with self._lock:
            if self._draining:
                return (f"drain of {sorted(self._draining)} in flight")
            if self._last_action_t is not None:
                since = now - self._last_action_t
                if since < self.config.cooldown_s:
                    return (f"{since:.1f}s since last action < cooldown "
                            f"{self.config.cooldown_s:g}s")
        return ""

    def _pick_victim(self, sense: Dict[str, Any]) -> Optional[str]:
        """LIFO over autoscaler-spawned replicas first (scale in what
        scale-out added), else the newest supervised live replica —
        never one already draining."""
        live = set(sense["live"])
        with self._lock:
            draining = set(self._draining)
            spawned = list(self._spawned)
        for rid in reversed(spawned):
            if rid in live and rid not in draining:
                return rid
        for rid in reversed(list(self.supervisor.status())):
            if rid in live and rid not in draining:
                return rid
        return None

    # -- actuators -------------------------------------------------------
    def _scale_out(self, why: str, now: float) -> dict:
        with self._lock:
            self._seq += 1
            rid = f"{self.replica_id_prefix}{self._seq}"
        # actuate OUTSIDE the lock: add_replica takes supervisor locks
        self.supervisor.add_replica(rid, model=self.model,
                                    aot_dir=self.aot_dir,
                                    extra_args=self.extra_args)
        with self._lock:
            self._spawned.append(rid)
            self._last_action_t = now
        return self._record("scale_out", why, f"spawned {rid}", now,
                            replica=rid)

    def _scale_in(self, victim: str, now: float) -> dict:
        # mark the drain BEFORE signalling: a concurrent tick must see
        # the cooldown the instant the victim starts draining
        with self._lock:
            self._draining[victim] = now
            self._last_action_t = now
        self.supervisor.drain(victim)
        return self._record("scale_in", "calm", f"draining {victim}",
                            now, replica=victim)

    # -- audit + metrics -------------------------------------------------
    def _record(self, action: str, reason: str, detail: str,
                now: float, replica: str = "") -> dict:
        entry = {"t": now, "action": action, "reason": reason,
                 "detail": detail, "count": 1}
        if replica:
            entry["replica"] = replica
        if _monitor.enabled():
            _monitor.counter(
                "autoscaler_decisions_total",
                "autoscaler decisions by action and typed reason "
                "(acts AND refusals — a decision is never silent)"
            ).labels(action=action, reason=reason).inc()
        with self._lock:
            last = self._audit[-1] if self._audit else None
            if (last is not None and last["action"] == action
                    and last["reason"] == reason
                    and last.get("replica") == entry.get("replica")):
                # coalesce the refusal storm; the counter above already
                # took the per-tick increment
                last["count"] += 1
                last["t"] = now
                last["detail"] = detail
            else:
                self._audit.append(entry)
            self._last_decision = dict(entry)
        logger.info("autoscaler: %s (%s) — %s", action, reason, detail)
        return entry

    def _publish_gauges(self, sense: Dict[str, Any]) -> None:
        if not _monitor.enabled():
            return
        _monitor.gauge("autoscaler_replicas",
                       "live replicas the autoscaler counts against "
                       "min/max").set(sense["replicas"])
        _monitor.gauge("autoscaler_hot",
                       "1 while the hot signal (SLO burn or pressure) "
                       "is present").set(1 if sense["hot"] else 0)
        _monitor.gauge("autoscaler_inflight_spawns",
                       "replicas spawned but not yet ready").set(
            sense["spawning"])
        _monitor.gauge("autoscaler_draining",
                       "scale-in drains in flight").set(
            len(sense["draining"]))

    def status(self) -> dict:
        """One snapshot for tooling (``tools/fleet_top.py``): the last
        sense, the last act/refusal, and the audit tail."""
        with self._lock:
            return {
                "config": dataclasses.asdict(self.config),
                "sense": dict(self._last_sense),
                "last_decision": (dict(self._last_decision)
                                  if self._last_decision else None),
                "spawned": list(self._spawned),
                "draining": dict(self._draining),
                "audit": [dict(e) for e in self._audit],
            }


_STATE_RANK = {"ok": 0, "warning": 1, "burning": 2}


def _worst(a: Optional[str], b: Optional[str]) -> str:
    """Worst-state merge over the ok < warning < burning order (unknown
    states rank below ok so they never mask a real signal)."""
    ra = _STATE_RANK.get(a, -1)
    rb = _STATE_RANK.get(b, -1)
    return (a if ra >= rb else b) or "unknown"
