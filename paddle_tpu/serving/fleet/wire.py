"""Fleet wire schema v1: the versioned JSON contract between router,
front-end and callers (docs/SERVING.md "Fleet tier — wire schema").

Everything that crosses the process boundary is defined HERE, once:

* array encoding (base64 raw bytes + dtype + shape — bit-exact, no
  float repr round-trip),
* the request body (feed/prompt, priority, SLO class, deadline),
* the typed-outcome -> HTTP status map: every one of the engine's typed
  terminal outcomes travels as a DISTINCT status plus a structured error
  body, so a router (or a curl) can tell a shed from an expired deadline
  from a dead bucket without parsing prose,
* trace propagation: the ``X-PT-Trace`` header carries
  ``SpanContext.to_wire()`` so the replica's request root joins the
  caller's trace (one trace id, debuggable fleet-wide via the flight
  recorder),
* error body -> typed exception reconstruction (the router raises the
  SAME classes callers already catch in-process).

``schema_version`` rides in every body; a front-end refuses versions it
does not speak with 400 rather than guessing.
"""
from __future__ import annotations

import base64
import json
from typing import Any, Dict, List, Optional

import numpy as np

from ...resilience.deadline import DeadlineExceeded
from ..engine import (BatchFailed, CircuitOpen, EngineStopped, Overloaded,
                      PoisonRequest, ServingError)

__all__ = [
    "WIRE_SCHEMA_VERSION", "TRACE_HEADER", "SLO_CLASSES",
    "encode_array", "decode_array", "encode_feed", "decode_feed",
    "status_for", "error_body", "error_from_body", "resolve_priority",
    "resolve_tenant", "response_is_unadmitted", "ReplicaLost",
    "WireError",
]

WIRE_SCHEMA_VERSION = 1

# request header carrying trace.SpanContext.to_wire() across the wire
TRACE_HEADER = "X-PT-Trace"

# SLO classes resolve to admission priorities when the caller does not
# pass an explicit priority (degraded-mode shedding keys on priority —
# docs/SERVING.md). Deployments with finer tiers pass priority directly.
SLO_CLASSES: Dict[str, int] = {"batch": 0, "standard": 1,
                               "interactive": 2}


class WireError(ValueError):
    """Malformed/unsupported wire payload (HTTP 400 — a caller bug, not
    a submitted request; it never enters any accounting)."""


class ReplicaLost(ServingError):
    """The replica's connection failed while it held (or may have held)
    this request: either the connection died after the request bytes
    went out (the replica may have admitted it — never retried, because
    a possibly-admitted request retried elsewhere could reach TWO
    outcomes), or no replica could be reached at all once the retry
    policy was exhausted. Always a typed terminal outcome, never a bare
    socket error."""

    def __init__(self, msg: str, replica: str = ""):
        self.replica = replica
        super().__init__(msg)


# ---------------------------------------------------------------------------
# arrays
# ---------------------------------------------------------------------------

def encode_array(a) -> dict:
    a = np.ascontiguousarray(np.asarray(a))
    return {"dtype": str(a.dtype), "shape": list(a.shape),
            "b64": base64.b64encode(a.tobytes()).decode("ascii")}


def decode_array(d) -> np.ndarray:
    if not isinstance(d, dict) or "b64" not in d:
        raise WireError(f"array payload must be "
                        f"{{dtype, shape, b64}}, got {type(d).__name__}")
    try:
        dt = np.dtype(d["dtype"])
        raw = base64.b64decode(d["b64"])
        a = np.frombuffer(raw, dtype=dt)
        return a.reshape([int(x) for x in d["shape"]]).copy()
    except WireError:
        raise
    except Exception as e:
        raise WireError(f"undecodable array payload "
                        f"({type(e).__name__}: {e})") from e


def encode_feed(feed: Dict[str, Any]) -> dict:
    return {n: encode_array(v) for n, v in feed.items()}


def decode_feed(d) -> Dict[str, np.ndarray]:
    if not isinstance(d, dict):
        raise WireError("feed must be a JSON object of name -> array")
    return {str(n): decode_array(v) for n, v in d.items()}


def resolve_priority(body: dict) -> int:
    """Explicit ``priority`` wins; else the ``slo_class`` mapping; else
    the standard tier."""
    if body.get("priority") is not None:
        return int(body["priority"])
    slo = body.get("slo_class")
    if slo is None:
        return SLO_CLASSES["standard"]
    if slo not in SLO_CLASSES:
        raise WireError(f"unknown slo_class {slo!r} "
                        f"(known: {sorted(SLO_CLASSES)})")
    return SLO_CLASSES[slo]


# accounting tenants become metric label values (fleet_tenant_*); the
# charset bound keeps hostile ids out of the exposition format and the
# length bound keeps one caller from exploding label cardinality storage
_TENANT_MAX_LEN = 64


def resolve_tenant(body: dict) -> Optional[str]:
    """The optional ``tenant`` field (wire schema v1, additive): a short
    accounting id string, validated here so a hostile value is a 400
    ``WireError`` — a caller bug, never a submitted request. ``None``
    when absent (the engine accounts it under its default tenant)."""
    tenant = body.get("tenant")
    if tenant is None:
        return None
    if not isinstance(tenant, str):
        raise WireError(f"tenant must be a string, "
                        f"got {type(tenant).__name__}")
    tenant = tenant.strip()
    if not tenant:
        return None
    if len(tenant) > _TENANT_MAX_LEN:
        raise WireError(f"tenant id longer than {_TENANT_MAX_LEN} chars")
    if not all(c.isalnum() or c in "-_.:@" for c in tenant):
        raise WireError(
            "tenant id may only contain alphanumerics and - _ . : @ "
            f"(got {tenant!r})")
    return tenant


# ---------------------------------------------------------------------------
# typed outcomes <-> HTTP
# ---------------------------------------------------------------------------

# every typed terminal outcome maps to a DISTINCT status — the router's
# admitted/unadmitted classification reads the status alone:
#   400 caller bug (never submitted)    429 shed at admission (unadmitted)
#   410 engine stopped/draining at
#       admission (unadmitted)          503 bucket quarantined
#   500 batch failed (admitted)         504 deadline exceeded (admitted)
_STATUS = (
    (Overloaded, 429),
    (CircuitOpen, 503),
    (EngineStopped, 410),
    (DeadlineExceeded, 504),
    (BatchFailed, 500),
    (WireError, 400),
)

# statuses a router may retry on a sibling when the error body does not
# say better: the replica normally REJECTED such a request at admission,
# so it reached no outcome there. The body's explicit "admitted" flag
# (set by the front-end, which knows whether submit() itself raised)
# always wins — an ADMITTED request that settled EngineStopped (engine
# stopped without drain, dispatch-thread crash) also travels as 410, and
# retrying it would give one request two outcomes.
UNADMITTED_STATUSES = frozenset({429, 410})


def response_is_unadmitted(status: int, body: Optional[dict]) -> bool:
    """May the router retry this response on a sibling? The front-end's
    explicit ``admitted`` flag is authoritative; the status-class map is
    the fallback for bodies that lack it."""
    err = (body or {}).get("error") or {}
    if "admitted" in err:
        return err["admitted"] is False
    return status in UNADMITTED_STATUSES


def status_for(exc: BaseException) -> int:
    for cls, code in _STATUS:
        if isinstance(exc, cls):
            return code
    if isinstance(exc, ValueError):
        return 400
    return 500


def error_body(exc: BaseException,
               admitted: Optional[bool] = None) -> dict:
    """The structured error body for a typed outcome (or a caller bug).
    Carries enough to reconstruct the SAME typed exception router-side.
    ``admitted`` records whether the request had been admitted when the
    error arose (the front-end knows; the router's retry policy reads
    it — see :func:`response_is_unadmitted`)."""
    err: Dict[str, Any] = {
        "type": type(exc).__name__,
        "message": str(exc),
        "trace_id": getattr(exc, "trace_id", "") or "",
        "transient": bool(getattr(exc, "transient", False)),
    }
    if admitted is not None:
        err["admitted"] = bool(admitted)
    if isinstance(exc, Overloaded):
        err["reason"] = exc.reason
    if isinstance(exc, CircuitOpen):
        err["bucket"] = exc.bucket
    if isinstance(exc, DeadlineExceeded):
        err.update(what=exc.what, budget_s=exc.budget_s,
                   elapsed_s=exc.elapsed_s)
    if isinstance(exc, PoisonRequest):
        err["fingerprint"] = exc.fingerprint
    if isinstance(exc, ReplicaLost):
        err["replica"] = exc.replica
    return {"schema_version": WIRE_SCHEMA_VERSION, "error": err}


def error_from_body(body: Optional[dict],
                    default_msg: str = "") -> BaseException:
    """Rebuild the typed exception a replica shipped (the router raises
    it locally, trace id intact). Unknown/missing types degrade to the
    ``ServingError`` base — still typed, never a bare RuntimeError."""
    err = (body or {}).get("error") or {}
    typ = err.get("type", "")
    msg = err.get("message") or default_msg or "remote serving error"
    if typ == "Overloaded":
        e: BaseException = Overloaded(msg,
                                      reason=err.get("reason", "remote"))
    elif typ == "CircuitOpen":
        e = CircuitOpen(msg, bucket=err.get("bucket", ""))
    elif typ == "EngineStopped":
        e = EngineStopped(msg)
    elif typ == "DeadlineExceeded":
        e = DeadlineExceeded(err.get("what", msg),
                             float(err.get("budget_s", 0.0)),
                             float(err.get("elapsed_s", 0.0)))
    elif typ == "PoisonRequest":
        # still travels as 500 (a BatchFailed subclass), but the caller
        # can tell "you poisoned the batch" from "the bucket is broken"
        e = PoisonRequest(msg, fingerprint=err.get("fingerprint", ""))
    elif typ == "BatchFailed":
        e = BatchFailed(msg)
    elif typ == "ReplicaLost":
        e = ReplicaLost(msg, replica=err.get("replica", ""))
    elif typ in ("WireError", "ValueError"):
        # the 400 class: a caller bug the replica never submitted —
        # surfaced as the same ValueError family it is in-process
        e = WireError(msg)
    else:
        e = ServingError(f"{typ or 'remote error'}: {msg}")
    tid = err.get("trace_id", "")
    if tid:
        e.trace_id = tid
    return e


# ---------------------------------------------------------------------------
# body plumbing
# ---------------------------------------------------------------------------

def dumps(obj) -> bytes:
    return json.dumps(obj).encode("utf-8")


def loads(raw: bytes) -> dict:
    try:
        obj = json.loads(raw.decode("utf-8"))
    except Exception as e:
        raise WireError(f"request body is not JSON "
                        f"({type(e).__name__}: {e})") from e
    if not isinstance(obj, dict):
        raise WireError("request body must be a JSON object")
    v = obj.get("schema_version", WIRE_SCHEMA_VERSION)
    try:
        v = int(v)
    except (TypeError, ValueError):
        raise WireError(f"wire schema_version must be an integer, "
                        f"got {v!r}") from None
    if v > WIRE_SCHEMA_VERSION:
        raise WireError(f"wire schema_version {v} is newer than this "
                        f"front-end speaks ({WIRE_SCHEMA_VERSION})")
    return obj


def encode_outputs(outs: List[np.ndarray], trace_id: str = "") -> dict:
    return {"schema_version": WIRE_SCHEMA_VERSION,
            "outputs": [encode_array(o) for o in outs],
            "trace_id": trace_id}


def decode_outputs(body: dict) -> List[np.ndarray]:
    return [decode_array(o) for o in body.get("outputs", ())]
