"""Fleet telemetry plane: versioned /metrics forms + cross-replica
aggregation (docs/OBSERVABILITY.md "Fleet telemetry plane").

Two halves, mirroring the health plane's replica/router split:

**Replica side** — :func:`metrics_json` renders the monitor registry as
a SCHEMA-VERSIONED JSON document (``METRICS_SCHEMA_VERSION``, key set
frozen exactly like the engine's health payload) that carries what the
Prometheus text form cannot: per-bucket trace exemplars, the SLO burn
state and the per-tenant ledger. The front-end serves both forms on
``GET /metrics`` / ``/metrics.json``.

**Aggregator side** — :class:`FleetAggregator` (router/supervisor side,
the same poll-thread pattern as ``FleetRouter``) scrapes every
replica's ``/metrics`` on an interval and turns N replica registries
into one fleet view:

* **windowed counter deltas** — per-second rates over the scrape
  window, counter-reset aware (a restarted replica's counters drop to
  zero; the delta clamps to the new absolute value instead of going
  negative);
* **exact histogram merge** — request-latency histograms merge
  bucket-wise via :func:`monitor.merge_histogram_snapshots` (fixed
  shared bucket layouts make the merge exact, and mismatched layouts
  are refused, never silently misbucketed), so fleet p50/p99 are
  computed from the SUMMED distribution, not averaged percentiles;
* **rollups** — published back into the LOCAL registry as
  ``fleet_agg_*`` gauges labeled ``{replica=...}`` per replica plus a
  ``replica="_fleet"`` total, so one scrape of the aggregator's own
  process sees the whole fleet;
* **typed scrape failures** — every failure is classified
  (``timeout`` / ``connect`` / ``http_<status>`` / ``corrupt``) and
  counted on ``fleet_scrape_failures_total{replica,kind}``; a failing
  replica DEGRADES to its last good snapshot marked ``stale`` with a
  growing ``scrape_age_s`` — the aggregator itself never crashes on a
  hostile or half-written metrics body.

The whole plane sits behind ``FLAGS_fleet_telemetry`` (default OFF):
``start()`` refuses to spawn the scrape thread while the flag is off,
and the exemplar rings replica-side are never allocated (the observe
path passes ``exemplar=None``), so the disabled path is a true no-op.
"""
from __future__ import annotations

import http.client
import json
import logging
import socket
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ... import monitor as _monitor
from ...flags import flag as _flag

__all__ = ["METRICS_SCHEMA_VERSION", "METRICS_SCHEMA_KEYS", "enabled",
           "metrics_json", "AggregatorConfig", "FleetAggregator"]

logger = logging.getLogger("paddle_tpu.serving.fleet")

# the JSON metrics document is a wire contract exactly like the health
# payload: the key set is FROZEN per version — additions bump the
# version and land in both this frozenset and the docs table
# (docs/OBSERVABILITY.md "metrics JSON schema").
METRICS_SCHEMA_VERSION = 1
METRICS_SCHEMA_KEYS = frozenset({
    "schema_version", "replica_id", "families", "exemplars", "slo",
    "tenants"})

# the fleet-total pseudo replica label on fleet_agg_* rollups; "_fleet"
# cannot collide with a real replica id (supervisor ids are r<N>-style)
FLEET_LABEL = "_fleet"

# the histogram the fleet latency rollup merges: the engine-side
# completed-request latency (identical default bucket layout on every
# replica, which is what makes the merge exact)
REQUEST_LATENCY_METRIC = "serving_request_latency_seconds"
OUTCOME_COUNTER = "serving_requests_total"
QUEUE_DEPTH_GAUGE = "serving_queue_depth"

_SLO_STATE_ORDER = ("ok", "warning", "burning")


def enabled() -> bool:
    """The plane's master switch (``FLAGS_fleet_telemetry``)."""
    return _monitor.telemetry_enabled()


# ---------------------------------------------------------------------------
# replica side: the versioned JSON form
# ---------------------------------------------------------------------------

def metrics_json(registry=None, replica_id: str = "",
                 slo: Optional[dict] = None,
                 tenants: Optional[dict] = None) -> dict:
    """The schema-versioned JSON metrics document for one replica.

    ``families`` is ``MetricsRegistry.to_dict()`` verbatim;
    ``exemplars`` maps histogram family name -> list of
    ``{"labels": ..., "buckets": {le: [{"trace_id", "value"}, ...]}}``
    (only label sets that recorded any); ``slo``/``tenants`` are the
    engine's ``slo_state()`` / ``tenant_accounting()`` payloads (None
    for engines without them).
    """
    reg = registry if registry is not None else _monitor.get_registry()
    exemplars: Dict[str, List[dict]] = {}
    for fam in reg.families():
        if fam.kind != "histogram":
            continue
        for labels, child in fam.children():
            ex = child.exemplars()
            if ex:
                exemplars.setdefault(fam.name, []).append(
                    {"labels": labels, "buckets": ex})
    return {
        "schema_version": METRICS_SCHEMA_VERSION,
        "replica_id": replica_id,
        "families": reg.to_dict(),
        "exemplars": exemplars,
        "slo": slo,
        "tenants": tenants,
    }


# ---------------------------------------------------------------------------
# aggregator side
# ---------------------------------------------------------------------------

class _Corrupt(Exception):
    """Internal: the scrape answered 200 with an undecodable body."""


class AggregatorConfig:
    """Scrape knobs. ``mode='json'`` scrapes ``/metrics.json`` (the
    full document: exemplars, SLO, tenants); ``mode='prom'`` scrapes
    the text form and reassembles histograms through the
    ``monitor.promtext`` parser — same rollups, no exemplar/tenant
    sections (the text form does not carry them)."""

    def __init__(self, scrape_interval_s: Optional[float] = None,
                 scrape_timeout_s: float = 2.0, mode: str = "json"):
        if scrape_interval_s is None:
            scrape_interval_s = float(_flag("fleet_scrape_interval_s"))
        if mode not in ("json", "prom"):
            raise ValueError(f"aggregator mode must be 'json' or "
                             f"'prom', got {mode!r}")
        self.scrape_interval_s = float(scrape_interval_s)
        self.scrape_timeout_s = float(scrape_timeout_s)
        self.mode = mode


class FleetAggregator:
    """See module docstring. ``targets`` is a callable returning the
    current ``[(replica_id, "host:port"), ...]`` membership (evaluated
    every poll, so supervisor restarts/reassigns are picked up within
    one scrape), or a ``FleetRouter``-shaped object exposing
    ``.replicas`` — use :meth:`for_router` for that spelling."""

    def __init__(self, targets, config: Optional[AggregatorConfig] = None):
        self.config = config or AggregatorConfig()
        if callable(targets):
            self._targets = targets
        elif hasattr(targets, "replicas"):
            router = targets
            self._targets = lambda: [(r.replica_id, r.address)
                                     for r in router.replicas]
        else:
            fixed = [(str(rid), str(addr)) for rid, addr in targets]
            self._targets = lambda: fixed
        # leaf lock: guards _scrapes only; registry publication happens
        # OUTSIDE it, so this lock never nests around the registry's
        self._lock = _monitor.make_lock("FleetAggregator._lock")
        self._scrapes: Dict[str, dict] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop_ev = threading.Event()

    @classmethod
    def for_router(cls, router,
                   config: Optional[AggregatorConfig] = None
                   ) -> "FleetAggregator":
        return cls(router, config)

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "FleetAggregator":
        """Spawn the scrape thread — a NO-OP while the plane is
        disabled (``FLAGS_fleet_telemetry=0``): no thread, no sockets,
        no registry writes."""
        if not enabled():
            logger.info("fleet aggregator: telemetry plane disabled "
                        "(FLAGS_fleet_telemetry=0) — not starting")
            return self
        if self._thread is not None:
            return self
        self.poll_now()
        self._stop_ev.clear()
        self._thread = threading.Thread(
            target=self._poll_loop, name="paddle_tpu-fleet-agg-scrape",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_ev.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(self.config.scrape_timeout_s + 2.0)

    def __enter__(self) -> "FleetAggregator":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    def _poll_loop(self) -> None:
        while not self._stop_ev.wait(self.config.scrape_interval_s):
            try:
                self.poll_now()
            except Exception:
                # the aggregator must never die to one bad poll round —
                # individual scrape failures are already typed; this
                # guards rollup bugs
                logger.exception("fleet aggregator: poll round failed")

    # -- scraping --------------------------------------------------------
    def poll_now(self) -> None:
        """One synchronous scrape of every current target, then rollup
        publication. Safe to call directly (tests, CLI one-shots)."""
        now = time.monotonic()
        records = []
        for replica_id, address in list(self._targets()):
            records.append(self._scrape_one(str(replica_id),
                                            str(address), now))
        with self._lock:
            self._scrapes = {r["replica_id"]: r for r in records}
        self._publish(records, now)

    def _fetch(self, address: str) -> Tuple[int, bytes]:
        host, _, port = address.rpartition(":")
        conn = http.client.HTTPConnection(
            host, int(port), timeout=self.config.scrape_timeout_s)
        try:
            path = ("/metrics.json" if self.config.mode == "json"
                    else "/metrics")
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    def _parse(self, raw: bytes) -> dict:
        """Decode one 200 body into the JSON-document shape (whatever
        the scrape mode). Anything undecodable is :class:`_Corrupt` —
        a typed scrape failure, never a partial parse."""
        if self.config.mode == "prom":
            try:
                parsed = _monitor.parse_prometheus_text(raw)
            except _monitor.PromParseError as e:
                raise _Corrupt(str(e)) from e
            return {"schema_version": METRICS_SCHEMA_VERSION,
                    "replica_id": "", "exemplars": {}, "slo": None,
                    "tenants": None,
                    "families": _families_from_prom(parsed)}
        try:
            body = json.loads(raw.decode("utf-8"))
        except Exception as e:
            raise _Corrupt(f"not JSON ({type(e).__name__})") from e
        if not isinstance(body, dict):
            raise _Corrupt("metrics body is not a JSON object")
        try:
            version = int(body.get("schema_version", 0))
        except (TypeError, ValueError):
            raise _Corrupt("bad metrics schema_version") from None
        if version > METRICS_SCHEMA_VERSION:
            raise _Corrupt(f"metrics schema_version {version} is newer "
                           f"than this aggregator speaks "
                           f"({METRICS_SCHEMA_VERSION})")
        if not isinstance(body.get("families"), dict):
            raise _Corrupt("metrics body has no families object")
        return body

    def _scrape_one(self, replica_id: str, address: str,
                    now: float) -> dict:
        with self._lock:
            prev = self._scrapes.get(replica_id)
        kind = ""
        body: Optional[dict] = None
        try:
            status, raw = self._fetch(address)
            if status != 200:
                kind = f"http_{status}"
            else:
                body = self._parse(raw)
        except _Corrupt as e:
            kind = "corrupt"
            logger.warning("fleet aggregator: corrupt /metrics from %s "
                           "(%s) — keeping last good snapshot", replica_id, e)
        except (socket.timeout, TimeoutError):
            kind = "timeout"
        except (OSError, http.client.HTTPException):
            kind = "connect"
        if _monitor.enabled():
            _monitor.counter(
                "fleet_scrapes_total",
                "aggregator scrape attempts by replica and result"
            ).labels(replica=replica_id,
                     result="ok" if body is not None else "error").inc()
            if body is None:
                _monitor.counter(
                    "fleet_scrape_failures_total",
                    "aggregator scrape failures by replica and typed "
                    "kind (timeout/connect/http_<status>/corrupt)"
                ).labels(replica=replica_id, kind=kind).inc()
        if body is None:
            # degrade: last good data survives, marked stale with a
            # growing age — never a crash, never silently fresh
            rec = dict(prev) if prev else self._fresh_record(replica_id)
            rec.update(
                replica_id=replica_id, up=False, stale=True, error=kind,
                consecutive_failures=rec.get("consecutive_failures",
                                             0) + 1)
            return rec
        families = body["families"]
        counters = _counter_values(families)
        rates: Dict[str, Dict[Tuple, float]] = {}
        window_s = None
        if prev is not None and prev.get("last_ok_monotonic") is not None:
            window_s = max(1e-9, now - prev["last_ok_monotonic"])
            for name, series in counters.items():
                prev_series = (prev.get("counters") or {}).get(name, {})
                for key, v in series.items():
                    d = v - prev_series.get(key, 0.0)
                    if d < 0:
                        d = v    # counter reset: replica restarted
                    rates.setdefault(name, {})[key] = d / window_s
        return {
            "replica_id": replica_id, "up": True, "stale": False,
            "error": "", "consecutive_failures": 0,
            "last_ok_monotonic": now, "window_s": window_s,
            "counters": counters, "rates": rates,
            "latency": _latency_snapshot(families),
            "outcomes": _outcome_counts(families),
            "queue_depth": _gauge_value(families, QUEUE_DEPTH_GAUGE),
            "slo": body.get("slo"), "tenants": body.get("tenants"),
            "exemplars": body.get("exemplars") or {},
        }

    @staticmethod
    def _fresh_record(replica_id: str) -> dict:
        return {"replica_id": replica_id, "up": False, "stale": True,
                "error": "", "consecutive_failures": 0,
                "last_ok_monotonic": None, "window_s": None,
                "counters": {}, "rates": {}, "latency": None,
                "outcomes": {}, "queue_depth": None, "slo": None,
                "tenants": None, "exemplars": {}}

    # -- rollups ---------------------------------------------------------
    def _publish(self, records: Sequence[dict], now: float) -> None:
        if not _monitor.enabled():
            return
        up = _monitor.gauge(
            "fleet_agg_up",
            "1 when the last scrape of this replica succeeded")
        age = _monitor.gauge(
            "fleet_agg_scrape_age_s",
            "seconds since this replica's last successful scrape "
            "(stale snapshots keep aging)")
        lat = _monitor.gauge(
            "fleet_agg_latency_seconds",
            "request latency quantiles from scraped histograms; "
            "replica='_fleet' is the EXACT bucket-wise merge across "
            "replicas, not an average of percentiles")
        rate = _monitor.gauge(
            "fleet_agg_request_rate",
            "completed requests per second over the scrape window")
        reqs = _monitor.gauge(
            "fleet_agg_requests_total",
            "absolute scraped request-outcome counters; "
            "replica='_fleet' sums all replicas")
        slo_g = _monitor.gauge(
            "fleet_agg_slo_state",
            "scraped SLO state per replica: 0=ok 1=warning 2=burning "
            "(-1 unknown); replica='_fleet' is the worst")
        for rec in records:
            rid = rec["replica_id"]
            up.labels(replica=rid).set(0.0 if rec["stale"] else 1.0)
            last_ok = rec.get("last_ok_monotonic")
            age.labels(replica=rid).set(
                (now - last_ok) if last_ok is not None else -1.0)
            snap = rec.get("latency")
            if snap:
                for q in ("p50", "p99"):
                    v = snap.get(q)
                    if v is not None:
                        lat.labels(replica=rid, q=q).set(v)
            completed_rate = (rec.get("rates", {})
                              .get(OUTCOME_COUNTER, {})
                              .get((("outcome", "completed"),)))
            if completed_rate is not None:
                rate.labels(replica=rid).set(completed_rate)
            for key, v in rec.get("outcomes", {}).items():
                reqs.labels(replica=rid, outcome=key).set(v)
            slo_g.labels(replica=rid).set(_slo_index(rec.get("slo")))
        fleet = self._fleet_rollup(records)
        up.labels(replica=FLEET_LABEL).set(
            sum(1.0 for r in records if not r["stale"]))
        if fleet["latency"]:
            for q in ("p50", "p99"):
                v = fleet["latency"].get(q)
                if v is not None:
                    lat.labels(replica=FLEET_LABEL, q=q).set(v)
        for key, v in fleet["outcomes"].items():
            reqs.labels(replica=FLEET_LABEL, outcome=key).set(v)
        slo_g.labels(replica=FLEET_LABEL).set(fleet["slo_index"])

    def _fleet_rollup(self, records: Sequence[dict]) -> dict:
        """The cross-replica reduction: exact latency merge, outcome
        sums, tenant-ledger sums, worst SLO state. Stale records
        contribute their LAST GOOD data (the honest fleet view while a
        replica is unreachable: known-old beats silently-absent — the
        per-replica ``stale``/``scrape_age_s`` marks carry the caveat)."""
        latencies = [r["latency"] for r in records if r.get("latency")]
        merged = None
        if latencies:
            try:
                merged = _monitor.merge_histogram_snapshots(latencies)
            except ValueError as e:
                # mismatched bucket layouts across replica versions:
                # refuse the merge loudly rather than misbucket
                logger.warning("fleet aggregator: latency merge "
                               "refused: %s", e)
        outcomes: Dict[str, float] = {}
        tenants: Dict[str, dict] = {}
        worst = -1
        for r in records:
            for key, v in (r.get("outcomes") or {}).items():
                outcomes[key] = outcomes.get(key, 0) + v
            for name, t in (r.get("tenants") or {}).items():
                agg = tenants.setdefault(name,
                                         {"outcomes": {},
                                          "occupancy_s": 0.0})
                for o, n in (t.get("outcomes") or {}).items():
                    agg["outcomes"][o] = agg["outcomes"].get(o, 0) + n
                agg["occupancy_s"] += float(t.get("occupancy_s") or 0.0)
                agg["quota_sheds"] = (agg.get("quota_sheds", 0)
                                      + int(t.get("quota_sheds") or 0))
            worst = max(worst, _slo_index(r.get("slo")))
        return {"latency": merged, "outcomes": outcomes,
                "tenants": tenants, "slo_index": worst,
                "slo_state": (_SLO_STATE_ORDER[worst]
                              if 0 <= worst < len(_SLO_STATE_ORDER)
                              else "unknown")}

    def snapshot(self) -> dict:
        """The fleet view for CLIs and the CI gate: per-replica scrape
        records (ages recomputed now) plus the fleet rollup."""
        now = time.monotonic()
        with self._lock:
            records = [dict(r) for r in self._scrapes.values()]
        for r in records:
            last_ok = r.get("last_ok_monotonic")
            r["scrape_age_s"] = ((now - last_ok)
                                 if last_ok is not None else None)
            # tuple label keys -> "k=v,..." strings so the snapshot is
            # JSON-serializable (the CI gate writes it to a report file)
            for field in ("counters", "rates"):
                r[field] = {name: {_label_str(k): v
                                   for k, v in series.items()}
                            for name, series in (r.get(field)
                                                 or {}).items()}
        fleet = self._fleet_rollup(records)
        if fleet["latency"]:
            fleet["p50"] = fleet["latency"].get("p50")
            fleet["p99"] = fleet["latency"].get("p99")
        else:
            fleet["p50"] = fleet["p99"] = None
        return {"replicas": {r["replica_id"]: r for r in records},
                "fleet": fleet}


# ---------------------------------------------------------------------------
# families-dict extraction helpers (shared by json and prom modes)
# ---------------------------------------------------------------------------

def _label_key(labels: dict) -> Tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _label_str(key: Tuple) -> str:
    return ",".join(f"{k}={v}" for k, v in key)


def _counter_values(families: dict) -> Dict[str, Dict[Tuple, float]]:
    out: Dict[str, Dict[Tuple, float]] = {}
    for name, fam in families.items():
        if not isinstance(fam, dict) or fam.get("kind") != "counter":
            continue
        series: Dict[Tuple, float] = {}
        for v in fam.get("values", ()):
            try:
                series[_label_key(v.get("labels") or {})] = \
                    float(v.get("value"))
            except (TypeError, ValueError, AttributeError):
                continue
        out[name] = series
    return out


def _outcome_counts(families: dict) -> Dict[str, float]:
    fam = families.get(OUTCOME_COUNTER) or {}
    out: Dict[str, float] = {}
    for v in fam.get("values", ()) if isinstance(fam, dict) else ():
        labels = v.get("labels") or {}
        key = labels.get("outcome")
        if key is None:
            continue
        try:
            out[key] = out.get(key, 0.0) + float(v.get("value"))
        except (TypeError, ValueError):
            continue
    return out


def _gauge_value(families: dict, name: str) -> Optional[float]:
    fam = families.get(name)
    if not isinstance(fam, dict):
        return None
    for v in fam.get("values", ()):
        if not (v.get("labels") or {}):
            try:
                return float(v.get("value"))
            except (TypeError, ValueError):
                return None
    return None


def _latency_snapshot(families: dict) -> Optional[dict]:
    """The (unlabeled) request-latency histogram snapshot, or None."""
    fam = families.get(REQUEST_LATENCY_METRIC)
    if not isinstance(fam, dict) or fam.get("kind") != "histogram":
        return None
    for v in fam.get("values", ()):
        if not (v.get("labels") or {}):
            snap = v.get("value")
            if isinstance(snap, dict) and isinstance(
                    snap.get("buckets"), dict):
                return snap
    return None


def _slo_index(slo: Optional[dict]) -> int:
    state = (slo or {}).get("state")
    try:
        return _SLO_STATE_ORDER.index(state)
    except ValueError:
        return -1


def _families_from_prom(parsed: dict) -> dict:
    """Reassemble ``parse_prometheus_text`` output into the JSON
    document's ``families`` shape, so the extraction helpers work on
    either scrape mode. Histogram label sets are regrouped (minus the
    parser's ``__series__``/``le`` bookkeeping labels) and rebuilt into
    snapshot dicts."""
    out: Dict[str, dict] = {}
    for name, fam in parsed.items():
        if fam.kind == "histogram":
            groups: Dict[Tuple, List] = {}
            for labels, v in fam.samples:
                base = {k: val for k, val in labels.items()
                        if k not in ("__series__", "le")}
                groups.setdefault(_label_key(base), []).append(
                    (labels, v))
            values = []
            for key, samples in groups.items():
                sub = _monitor.ParsedFamily(name)
                sub.samples = samples
                values.append({"labels": dict(key),
                               "value":
                               _monitor.histogram_snapshot_from_samples(
                                   sub)})
            out[name] = {"kind": "histogram", "help": fam.help or "",
                         "values": values}
        else:
            out[name] = {
                "kind": fam.kind or "gauge", "help": fam.help or "",
                "values": [{"labels": dict(labels), "value": v}
                           for labels, v in fam.samples]}
    return out
