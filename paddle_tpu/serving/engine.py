"""ServingEngine: continuous batching over compiled executables.

The online-serving counterpart of ``Executor.run`` (ROADMAP item 1; the
reference tree's ``paddle/fluid/inference`` server role). One engine owns
one inference program + one scope of loaded parameters + one
:class:`~paddle_tpu.executor.Executor`, and turns arbitrary concurrent
traffic into a small set of padded shape buckets so a handful of AOT
executables absorbs everything:

* callers ``submit()`` single requests from any thread — admission
  control answers immediately (accept, or a TYPED rejection; never a
  silent drop);
* a dedicated dispatch thread drains the queue, groups requests by feed
  signature, pads the concatenated batch up to the next power-of-two
  bucket, and runs the executor while callers wait on futures — the
  device stays busy while the host batches;
* every admitted request reaches EXACTLY ONE terminal outcome: a
  response, :class:`DeadlineExceeded`, :class:`Overloaded`,
  :class:`CircuitOpen`, :class:`BatchFailed` or :class:`EngineStopped`.
  ``accounting()`` exposes the exact ints; ``tools/load_check.py`` gates
  on ``submitted == sum(outcomes)`` under injected chaos.

Robustness surface (docs/SERVING.md):

* **deadlines** — each request carries a ``resilience.Deadline`` (the
  same implementation the retry budgets use); expired requests are swept
  to ``DeadlineExceeded`` before they waste a batch slot.
* **admission control / load shedding** — bounded queue depth and
  oldest-request age; over either bound new arrivals get ``Overloaded``.
* **circuit breaker** — per shape bucket (``serving.breaker``): repeated
  batch failures quarantine the bucket, cooling down through the
  ``resilience.retry`` backoff schedule, half-open probe, close on
  success.
* **graceful degradation** — sustained pressure halves the batch ceiling
  (bounding per-batch latency) and sheds sub-priority requests; both
  restore when pressure clears.
* **fault isolation** — a failing batch (injected fault, compile
  failure past the retry budget, ``FLAGS_check_nan_inf`` trip, watchdog
  timeout on a hung step) fails only that batch's requests, typed; the
  engine keeps serving. The ``hang`` fault site fires inside the
  executor's watchdog-armed section, and the watchdog can now break
  non-main threads, so a slow batch dies diagnosed.
* **poison-request bisection** — with ``FLAGS_serving_bisect_depth > 0``
  a failed batch whose error is state-safe is re-dispatched as bisected
  halves (bounded depth, per-member deadlines still enforced) until the
  culprit is isolated: innocents complete with correct results, the
  culprit settles typed :class:`PoisonRequest` and its feed fingerprint
  enters a bounded quarantine that sheds repeat offenders at admission.
  Failures that may have corrupted device state (watchdog timeout,
  device loss, consumed donated buffers) still fail the whole batch —
  never a re-dispatch on corrupted state.

Fault sites for the chaos gate: ``enqueue`` (submission), ``overload``
(forced shed), ``batch_dispatch`` (batch failure) + the executor's own
``compile``/``step``/``hang``. SLO metrics land on ``paddle_tpu.monitor``
(docs/OBSERVABILITY.md): request latency histogram with p50/p99, queue
depth, batch occupancy, shed/deadline/breaker counters.
"""
from __future__ import annotations

import dataclasses
import itertools
import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from .. import monitor as _monitor
from .. import trace as _trace
from ..executor import Executor, Scope
from ..framework import Variable
from ..resilience import faults as _faults
from ..resilience.deadline import Deadline, DeadlineExceeded
from .breaker import CircuitBreaker
from .slo import SloBurnTracker, parse_latency_targets

__all__ = ["ServingConfig", "ServingEngine", "ServingFuture",
           "ServingError", "Overloaded", "CircuitOpen", "BatchFailed",
           "PoisonRequest", "EngineStopped", "DeadlineExceeded",
           "HEALTH_SCHEMA_VERSION", "HEALTH_SCHEMA_KEYS",
           "DEFAULT_TENANT", "parse_tenant_weights"]

logger = logging.getLogger("paddle_tpu.serving")

OCCUPANCY_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)

# The health()/ready() payload is a WIRE CONTRACT since the fleet tier:
# the router's load-aware dispatch reads these keys over HTTP, so the
# schema is versioned and frozen (docs/SERVING.md "Health probe schema").
# Adding a key is a minor change (bump nothing, document it); renaming or
# removing one breaks deployed routers and requires a version bump plus a
# compatibility note. tests/test_fleet.py regression-tests this set.
HEALTH_SCHEMA_VERSION = 1
HEALTH_SCHEMA_KEYS = frozenset({
    "schema_version", "status", "ready", "queue_depth", "queue_limit",
    "degraded", "current_max_batch", "open_buckets", "accounting",
    # additive since the telemetry plane (documented minor change,
    # docs/SERVING.md "SLO burn rate"): the engine's multi-window SLO
    # burn state — ok | warning | burning per priority class
    "slo",
})

# requests that arrive without a tenant id (the wire field is optional)
# are accounted under this name so the per-tenant ledger still sums
# exactly to the fleet ledger
DEFAULT_TENANT = "anonymous"


# ---------------------------------------------------------------------------
# typed terminal outcomes
# ---------------------------------------------------------------------------

class ServingError(RuntimeError):
    """Base of every typed serving rejection/failure. ``transient =
    False``: the retry classifier must never absorb one — each is a
    deliberate terminal outcome, not an infrastructure hiccup.
    ``trace_id`` names the request's trace when ``FLAGS_trace`` is on
    (every typed outcome is attributable to one specific request —
    ``accounting()['recent_outcomes']`` carries the same ids)."""

    transient = False
    trace_id = ""


class Overloaded(ServingError):
    """Admission control shed this request (queue depth/age bound,
    degraded-mode priority shed, or injected overload pressure).
    ``reason`` names which bound tripped."""

    def __init__(self, msg: str, reason: str = "queue_full"):
        self.reason = reason
        super().__init__(msg)


class CircuitOpen(ServingError):
    """The request's shape bucket is quarantined by its circuit breaker
    (repeated batch failures); retry after the cooldown."""

    def __init__(self, msg: str, bucket: str = ""):
        self.bucket = bucket
        super().__init__(msg)


class BatchFailed(ServingError):
    """The batch this request was dispatched in failed; ``__cause__`` is
    the underlying error (injected fault, compile giveup, nan trip,
    watchdog timeout). Only this batch failed — the engine keeps
    serving."""


class PoisonRequest(BatchFailed):
    """Bisection isolated THIS request as the culprit of its batch's
    failure (``FLAGS_serving_bisect_depth``): re-dispatched alone (or as
    the sole survivor of bisected halves) it still failed, while its
    former batch mates completed. ``__cause__`` is the underlying error;
    ``fingerprint`` names the quarantined feed — repeat submissions of
    the same feed are shed at admission (``Overloaded``,
    ``reason="poison_quarantine"``) instead of failing another batch."""

    def __init__(self, msg: str, fingerprint: str = ""):
        self.fingerprint = fingerprint
        super().__init__(msg)


class EngineStopped(ServingError):
    """The engine is not running (never started, or stopped without
    drain while this request was queued)."""


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------

def _flag_default(value, name):
    from ..flags import flag

    return flag(name) if value is None else value


@dataclasses.dataclass
class ServingConfig:
    """Engine knobs. ``None`` fields resolve from the ``FLAGS_serving_*``
    family at engine construction (docs/SERVING.md flag table), so a
    deployment can be tuned entirely through flags while tests pass
    explicit values."""

    max_batch: Optional[int] = None
    queue_depth: Optional[int] = None
    queue_age_s: Optional[float] = None
    deadline_s: Optional[float] = None          # 0 = no default deadline
    batch_window_s: Optional[float] = None
    breaker_threshold: Optional[int] = None
    breaker_cooldown_s: Optional[float] = None
    degrade_after_s: Optional[float] = None
    recover_after_s: Optional[float] = None
    degraded_min_priority: Optional[int] = None
    bisect_depth: Optional[int] = None          # 0 = no poison bisection
    bisect_quarantine: Optional[int] = None
    # SLO objectives ('class:seconds,...' latency targets + the error
    # budget and burn windows; serving/slo.py)
    slo_latency: Optional[str] = None
    slo_error_budget: Optional[float] = None
    slo_fast_window_s: Optional[float] = None
    slo_slow_window_s: Optional[float] = None
    # per-tenant quotas + weighted fair share (docs/SERVING.md "Fleet
    # control loop"): off by default — admission/dispatch identical to
    # the pre-tenant engine unless turned on
    tenant_fair_share: Optional[bool] = None
    tenant_weights: Optional[str] = None        # 'tenant:weight,...'
    tenant_quota_frac: Optional[float] = None

    def resolve(self) -> "ServingConfig":
        r = ServingConfig(
            max_batch=int(_flag_default(self.max_batch,
                                        "serving_max_batch")),
            queue_depth=int(_flag_default(self.queue_depth,
                                          "serving_queue_depth")),
            queue_age_s=float(_flag_default(self.queue_age_s,
                                            "serving_queue_age_s")),
            deadline_s=float(_flag_default(self.deadline_s,
                                           "serving_deadline_s")),
            batch_window_s=float(_flag_default(self.batch_window_s,
                                               "serving_batch_window_s")),
            breaker_threshold=int(_flag_default(
                self.breaker_threshold, "serving_breaker_threshold")),
            breaker_cooldown_s=float(_flag_default(
                self.breaker_cooldown_s, "serving_breaker_cooldown_s")),
            degrade_after_s=float(_flag_default(
                self.degrade_after_s, "serving_degrade_after_s")),
            recover_after_s=float(_flag_default(
                self.recover_after_s, "serving_recover_after_s")),
            degraded_min_priority=int(_flag_default(
                self.degraded_min_priority, "serving_degraded_min_priority")),
            bisect_depth=int(_flag_default(self.bisect_depth,
                                           "serving_bisect_depth")),
            bisect_quarantine=int(_flag_default(
                self.bisect_quarantine, "serving_bisect_quarantine")),
            slo_latency=str(_flag_default(self.slo_latency,
                                          "serving_slo_latency_s")),
            slo_error_budget=float(_flag_default(
                self.slo_error_budget, "serving_slo_error_budget")),
            slo_fast_window_s=float(_flag_default(
                self.slo_fast_window_s, "serving_slo_fast_window_s")),
            slo_slow_window_s=float(_flag_default(
                self.slo_slow_window_s, "serving_slo_slow_window_s")),
            tenant_fair_share=bool(_flag_default(
                self.tenant_fair_share, "serving_tenant_fair_share")),
            tenant_weights=str(_flag_default(
                self.tenant_weights, "serving_tenant_weights")),
            tenant_quota_frac=float(_flag_default(
                self.tenant_quota_frac, "serving_tenant_quota_frac")),
        )
        if r.max_batch < 1:
            raise ValueError(f"serving: max_batch must be >= 1, got "
                             f"{r.max_batch}")
        if r.queue_depth < 1:
            raise ValueError(f"serving: queue_depth must be >= 1, got "
                             f"{r.queue_depth}")
        if not 0.0 < r.tenant_quota_frac <= 1.0:
            raise ValueError(f"serving: tenant_quota_frac must be in "
                             f"(0, 1], got {r.tenant_quota_frac}")
        parse_tenant_weights(r.tenant_weights)  # validate the spec early
        return r


def parse_tenant_weights(spec: str) -> Dict[str, float]:
    """Parse a ``'tenant:weight,...'`` fair-share spec (the
    ``FLAGS_serving_tenant_weights`` format) into a dict. Unlisted
    tenants weigh 1. Malformed entries raise ``ValueError`` at config
    resolve time — never mid-admission."""
    weights: Dict[str, float] = {}
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        name, sep, raw = entry.rpartition(":")
        if not sep or not name:
            raise ValueError(f"serving: bad tenant weight entry "
                             f"{entry!r} (want 'tenant:weight')")
        try:
            w = float(raw)
        except ValueError:
            raise ValueError(f"serving: bad tenant weight {raw!r} "
                             f"for tenant {name!r}") from None
        if w <= 0:
            raise ValueError(f"serving: tenant weight must be > 0, "
                             f"got {w} for tenant {name!r}")
        weights[name.strip()] = w
    return weights


# ---------------------------------------------------------------------------
# request + future
# ---------------------------------------------------------------------------

class ServingFuture:
    """One request's pending terminal outcome. Settled exactly once by
    the engine; a second settle attempt is an engine bug and raises.
    ``trace_id`` (non-empty under ``FLAGS_trace``) names the request's
    trace — the handle for pulling its span chain from the collector.

    Generative requests additionally STREAM: the engine emits tokens as
    decode chunks finish (``tokens()``/``stream()``). Intermediate tokens
    are *partial results*, not outcomes — the exactly-one-terminal-outcome
    accounting invariant is untouched: however many tokens streamed, the
    request still settles exactly once (a completed result carrying the
    full token array, or a typed error such as a mid-stream
    ``DeadlineExceeded``, after which no further token can be emitted)."""

    trace_id = ""

    def __init__(self):
        self._event = threading.Event()
        self._lock = _monitor.make_lock("ServingFuture._lock")
        self._result: Optional[List[np.ndarray]] = None
        self._error: Optional[BaseException] = None
        # streamed partial results (generative requests): guarded by
        # _lock, waiters ride the shared-lock condition
        self._tokens: List[Any] = []
        self._stream_cond = _monitor.make_condition(
            "ServingFuture._stream_cond", self._lock)

    def done(self) -> bool:
        return self._event.is_set()

    # -- streaming (generative requests) ---------------------------------
    def tokens(self) -> List[Any]:
        """Snapshot of the tokens streamed so far (partial results; also
        the salvage after a mid-stream typed failure)."""
        with self._lock:
            return list(self._tokens)

    def stream(self, timeout: Optional[float] = None):
        """Yield tokens as the engine emits them. Ends with normal
        iterator exhaustion on a completed request; raises the typed
        terminal error after yielding every token emitted before it (a
        mid-stream ``DeadlineExceeded`` surfaces here, with the partial
        tokens already delivered). ``timeout`` bounds each wait for the
        NEXT token — expiry raises ``TimeoutError`` without cancelling
        the request."""
        i = 0
        while True:
            with self._stream_cond:
                while i >= len(self._tokens) and not self._event.is_set():
                    if not self._stream_cond.wait(timeout):
                        raise TimeoutError(
                            "serving: stream() wait for the next token "
                            "timed out; the request is still pending "
                            "(not cancelled)")
                batch = self._tokens[i:]
                settled = self._event.is_set()
            for t in batch:
                yield t
            i += len(batch)
            if settled and i >= len(self.tokens()):
                if self._error is not None:
                    raise self._error
                return

    def _emit_tokens(self, toks: Sequence[Any]) -> None:
        """Engine side: append partial results and wake stream waiters.
        Emitting after the terminal outcome is an engine bug — the
        settle is the LAST word on a request."""
        with self._stream_cond:
            if self._event.is_set():
                raise RuntimeError(
                    "serving internal error: token emitted after the "
                    "request's terminal outcome")
            self._tokens.extend(toks)
            self._stream_cond.notify_all()

    def result(self, timeout: Optional[float] = None) -> List[np.ndarray]:
        """The fetch arrays (rows of this request only), or raises the
        typed terminal error. ``timeout`` is a local wait bound — it does
        NOT cancel the request (the engine still settles it)."""
        if not self._event.wait(timeout):
            raise TimeoutError("serving: result() wait timed out; the "
                               "request is still pending (not cancelled)")
        if self._error is not None:
            raise self._error
        return self._result

    def exception(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("serving: exception() wait timed out")
        return self._error

    # -- engine side -----------------------------------------------------
    def _settle(self, result=None, error=None) -> None:
        with self._lock:
            if self._event.is_set():
                raise RuntimeError(
                    "serving internal error: second terminal outcome for "
                    "one request (exactly-once accounting violated)")
            self._result, self._error = result, error
            self._event.set()
            # stream() waiters must observe the terminal outcome too
            self._stream_cond.notify_all()


@dataclasses.dataclass
class _Request:
    seq: int
    feed: Dict[str, np.ndarray]
    nrows: int
    sig: tuple
    priority: int
    deadline: Optional[Deadline]
    submitted: float
    future: ServingFuture
    # sha256 feed fingerprint (computed only when poison bisection is on:
    # the quarantine's key, stable across resubmissions of one feed)
    fp: str = ""
    # accounting tenant (wire schema v1 optional field; DEFAULT_TENANT
    # when the caller sent none)
    tenant: str = DEFAULT_TENANT
    # root span of this request's trace (trace.NOOP_SPAN when off) and
    # the in-flight dispatch child opened by the dispatch thread
    span: Any = _trace.NOOP_SPAN
    dispatch_span: Any = _trace.NOOP_SPAN


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------

class ServingEngine:
    """See module docstring. Construction wires program/scope/executor;
    ``start()`` spawns the dispatch thread; ``submit()`` is thread-safe.

    The program must be an inference program (e.g. ``clone(for_test=True)``
    or ``io.load_inference_model``) whose parameters are already in
    ``scope`` — the engine never mutates the program and shares one
    compiled executable per (feed signature, bucket) through the
    executor's (now lock-guarded) step cache."""

    _seq = itertools.count()

    def __init__(self, program, feed_names: Sequence[str], fetch_list,
                 scope: Optional[Scope] = None, place=None,
                 executor: Optional[Executor] = None,
                 config: Optional[ServingConfig] = None):
        self._program = program
        self._feed_names = [f.name if isinstance(f, Variable) else f
                            for f in feed_names]
        self._fetch_names = [f.name if isinstance(f, Variable) else f
                             for f in (fetch_list or [])]
        self._scope = scope if scope is not None else Scope()
        self._exe = executor or Executor(place)
        self.config = (config or ServingConfig()).resolve()
        # injectable monotonic clock (the autoscaler's `_now` idiom):
        # every pressure/degradation/deadline-sweep window reads THIS, so
        # tests drive the sustain windows deterministically instead of
        # racing wall-clock sleeps against the dispatch thread
        self._now = time.monotonic

        self._lock = _monitor.make_lock("ServingEngine._lock")
        self._work = _monitor.make_condition("ServingEngine._work",
                                             self._lock)
        self._queue: List[_Request] = []
        self._running = False
        self._stopped = False
        self._drain = True
        self._thread: Optional[threading.Thread] = None
        # wakes the dispatch thread's retry-backoff sleeps at stop():
        # a multi-second compile backoff must never block teardown
        # (resilience.retry.set_thread_stop_event)
        self._stop_ev = threading.Event()
        # graceful-preemption wiring (install_preemption_handler)
        self._preempt_unregister: Optional[Callable[[], None]] = None
        self._preempt_signals_held = False

        # degradation state (guarded by _lock)
        self._degraded = False
        self._cur_max_batch = self.config.max_batch
        self._pressure_since: Optional[float] = None
        self._calm_since: Optional[float] = None

        # per-bucket breakers; inserted by the dispatch thread under
        # _lock so health probes can snapshot the dict from any thread
        self._breakers: Dict[tuple, CircuitBreaker] = {}
        # requests taken off the queue but not yet settled (their batch
        # is executing): part of accounting()'s pending count
        self._dispatched = 0
        # the batch currently executing (dispatch thread only; read by
        # the crash guard to settle in-flight requests typed)
        self._current_batch: List[_Request] = []

        # bounded poison quarantine (guarded by _lock): feed fingerprint
        # -> times shed at admission since isolation; oldest evicted at
        # config.bisect_quarantine entries
        from collections import OrderedDict

        self._quarantine: "OrderedDict[str, int]" = OrderedDict()

        # exact request accounting (guarded by _lock): the load gate's
        # ground truth. submitted == sum(all other keys) + pending queue
        self._acct = {"submitted": 0, "completed": 0, "failed": 0,
                      "poisoned": 0, "shed": 0, "deadline_exceeded": 0,
                      "circuit_open": 0, "rejected_fault": 0,
                      "rejected_stopped": 0}
        # last N terminal outcomes with their trace ids (accounting()):
        # a failed load_check leg names the exact requests that missed
        self._recent_outcomes: deque = deque(maxlen=64)

        # SLO burn-rate tracker (serving/slo.py): fed one observation per
        # terminal outcome from _finish_request, serialized into the
        # health payload's "slo" key. Leaf-locked — it never acquires the
        # engine lock, so feeding it under _lock cannot deadlock.
        self._slo = SloBurnTracker(
            parse_latency_targets(self.config.slo_latency),
            error_budget=self.config.slo_error_budget,
            fast_window_s=self.config.slo_fast_window_s,
            slow_window_s=self.config.slo_slow_window_s)
        # per-tenant terminal-outcome ledger (tenant_accounting()): its
        # own leaf lock for the same reason — _finish_request runs both
        # with and without the engine lock held
        self._tenant_lock = _monitor.make_lock("ServingEngine._tenant_lock")
        self._tenant_ledger: Dict[str, dict] = {}

        # weighted fair share (guarded by _lock; docs/SERVING.md "Fleet
        # control loop"): parsed weight table plus the stride-scheduler
        # pass values — a tenant's pass advances by rows/weight on every
        # dispatch, and the anchor request of the next batch comes from
        # the queued tenant with the smallest pass. Only consulted when
        # config.tenant_fair_share is on; the table is bounded by
        # eviction of tenants with nothing queued.
        self._tenant_weights = parse_tenant_weights(
            self.config.tenant_weights)
        self._tenant_pass: Dict[str, float] = {}

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "ServingEngine":
        with self._lock:
            if self._stopped:
                raise EngineStopped("serving: engine was stopped; build a "
                                    "fresh ServingEngine")
            if self._running:
                return self
            self._running = True
        self._thread = threading.Thread(target=self._dispatch_loop,
                                        name="paddle_tpu-serving-dispatch",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop serving. ``drain=True`` lets the dispatcher finish every
        queued request first; ``drain=False`` fails queued requests with
        typed :class:`EngineStopped`. Either way each queued request
        still reaches exactly one terminal outcome. A retry backoff in
        progress on the dispatch thread is woken immediately (its batch
        fails typed) — stop() never waits out an exponential backoff."""
        with self._lock:
            self._running = False
            self._stopped = True
            self._drain = drain
            self._work.notify_all()
        self._stop_ev.set()
        # take-and-clear under the lock: a preemption callback thread and
        # the owner's stop() can race here, and a double release would
        # decrement the shared signal-handler refcount twice (tearing
        # down another owner's graceful route)
        with self._lock:
            unregister, self._preempt_unregister = \
                self._preempt_unregister, None
            held, self._preempt_signals_held = \
                self._preempt_signals_held, False
        if unregister is not None:
            unregister()
        if held:
            # release this engine's refcounted hold on the SIGTERM
            # handler (another owner's hold keeps it installed; from a
            # non-main thread the restore is a no-op and the harmless
            # event-setting handler simply stays)
            from ..resilience import graceful as _graceful

            _graceful.uninstall_signal_handlers()
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                logger.error("serving: dispatch thread did not exit within "
                             "%gs at stop()", timeout)

    def install_preemption_handler(self) -> bool:
        """Graceful preemption (resilience.graceful): route SIGTERM into
        a drain-stop of this engine — admission closes, every queued
        request still reaches its typed terminal outcome, ``ready()``
        flips false so the balancer routes away, and the process can
        exit 0. Returns whether a signal handler could be installed
        (main thread only); the shutdown-event registration happens
        either way, so an externally-raised ``request_shutdown()``
        drains the engine too."""
        from ..resilience import graceful as _graceful

        # under the engine lock: stop() swaps these same fields from the
        # preemption-callback thread, and an unlocked install racing it
        # would leak a callback + signal-handler hold on a dead engine.
        # (Lock order is engine -> graceful only; the late-registration
        # path dispatches callbacks on a fresh thread, never inline.)
        with self._lock:
            if self._stopped:
                return False
            if self._preempt_unregister is None:
                self._preempt_unregister = _graceful.on_shutdown(
                    lambda: self.stop(drain=True))
            if not self._preempt_signals_held:
                self._preempt_signals_held = \
                    _graceful.install_signal_handlers()
            return self._preempt_signals_held

    def __enter__(self) -> "ServingEngine":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop(drain=True)
        return False

    # -- submission ------------------------------------------------------
    def submit(self, feed: Dict[str, Any], *, priority: int = 0,
               deadline_s: Optional[float] = None,
               trace_parent=None,
               tenant: Optional[str] = None) -> ServingFuture:
        """Admit one request (any thread). ``feed`` maps every declared
        feed name to an array with a leading batch dim (usually 1).
        Raises a typed :class:`ServingError` subclass when rejected —
        that raise IS the request's terminal outcome. ``trace_parent``
        (a ``trace.Span``/``SpanContext``, e.g. reconstructed from the
        fleet wire headers) parents the request's root span so one trace
        id follows the request across processes. ``tenant`` attributes
        the request in the per-tenant ledger (``tenant_accounting()``
        and the ``fleet_tenant_*`` metrics); absent means
        :data:`DEFAULT_TENANT`."""
        # validation first: a malformed feed (ValueError) is a caller bug,
        # not a submitted request — it never enters the accounting
        req = self._build_request(feed, priority, deadline_s, trace_parent,
                                  tenant)
        # admission runs as a child span of the request root, so a typed
        # rejection still ships a complete (if short) trace
        sub = _trace.start_span("serving.submit", parent=req.span,
                                priority=req.priority, rows=req.nrows)
        return self._admit_and_enqueue(req, sub)

    def _admit_and_enqueue(self, req: _Request, sub) -> ServingFuture:
        """The admission sequence shared by every submit flavour
        (request/response and generative): accounting, the enqueue fault
        point, the stopped check, admission control, the enqueue span and
        the dispatcher wake. Every rejection is a typed terminal
        outcome."""
        with self._lock:
            self._acct["submitted"] += 1
        try:
            # injected submission failure: typed outcome at the caller
            _faults.fault_point("enqueue")
        except _faults.InjectedFault as e:
            sub.end(error=e)
            self._account("rejected_fault")
            self._finish_request(req, "rejected_fault", e)
            raise
        now = self._now()
        with self._lock:
            if not self._running:
                self._acct["rejected_stopped"] += 1
                self._record_outcome("rejected_stopped")
                err = EngineStopped("serving: engine not running")
                sub.end(error=err)
                self._finish_request(req, "rejected_stopped", err)
                raise err
            try:
                self._admit_locked(req, now)   # raises Overloaded on shed
            except Overloaded as e:
                sub.end(error=e)
                self._finish_request(req, "shed", e)
                raise
            sub.end()
            _trace.start_span("serving.enqueue", parent=req.span,
                              queue_depth=len(self._queue)).end()
            self._queue.append(req)
            self._gauge_depth_locked()
            self._work.notify()
        return req.future

    def _build_request(self, feed, priority, deadline_s,
                       trace_parent=None, tenant=None) -> _Request:
        vals = {}
        nrows = None
        for n in self._feed_names:
            if n not in feed:
                raise ValueError(f"serving: feed missing declared input "
                                 f"'{n}' (need {self._feed_names})")
            a = np.asarray(feed[n])
            if a.ndim == 0:
                raise ValueError(f"serving: feed '{n}' must have a leading "
                                 f"batch dim")
            if nrows is None:
                nrows = int(a.shape[0])
            elif int(a.shape[0]) != nrows:
                raise ValueError(
                    f"serving: inconsistent batch dims in one request: "
                    f"'{n}' has {a.shape[0]}, expected {nrows}")
            vals[n] = a
        if not vals:
            raise ValueError("serving: empty feed")
        if nrows > self.config.max_batch:
            raise ValueError(
                f"serving: request rows {nrows} exceed max_batch "
                f"{self.config.max_batch}; split the request")
        sig = tuple((n, tuple(vals[n].shape[1:]), str(vals[n].dtype))
                    for n in self._feed_names)
        budget = self.config.deadline_s if deadline_s is None else deadline_s
        seq = next(ServingEngine._seq)
        dl = Deadline(budget, what=f"serving request #{seq}") \
            if budget and budget > 0 else None
        tenant = str(tenant).strip() if tenant is not None else ""
        req = _Request(seq=seq, feed=vals, nrows=nrows, sig=sig,
                       priority=int(priority), deadline=dl,
                       submitted=self._now(), future=ServingFuture(),
                       tenant=tenant or DEFAULT_TENANT)
        if self.config.bisect_depth > 0 and self._quarantine:
            # the fingerprint is only needed eagerly for the admission
            # quarantine lookup; with an empty quarantine the submit hot
            # path skips the hash (the poison-settle path computes it
            # lazily when a culprit is isolated)
            req.fp = self._feed_fingerprint(vals)
        # one trace per request, minted at submit: the root span stays
        # open across the queue + the dispatch thread and is settled with
        # the typed terminal outcome (exactly once, like the accounting).
        # A trace_parent carried over the fleet wire keeps the CALLER's
        # trace id instead of minting a fresh one, so one id is
        # debuggable router -> frontend -> engine -> flight recorder
        req.span = self._request_root(trace_parent, seq=seq, rows=nrows,
                                      priority=int(priority))
        req.future.trace_id = req.span.trace_id
        return req

    @staticmethod
    def _request_root(trace_parent, **attrs):
        if trace_parent is not None:
            return _trace.start_span("serving.request",
                                     parent=trace_parent, **attrs)
        return _trace.root_span("serving.request", **attrs)

    def _admit_locked(self, req: _Request, now: float) -> None:
        """Admission control under ``_lock``: raises typed Overloaded on
        any shed. Every rejection is accounted before it raises."""
        try:
            _faults.fault_point("overload")
        except _faults.InjectedFault as e:
            self._shed_locked("injected", now)
            raise Overloaded("serving: injected overload pressure "
                             "(FLAGS_fault_plan)", reason="injected") from e
        if self._quarantine and not req.fp \
                and self.config.bisect_depth > 0:
            # the lazy build-time hash saw an empty quarantine, but one
            # filled up since (e.g. this very feed's first copy was just
            # isolated on the dispatch thread): close the race under the
            # lock so a known-poison feed can never slip past admission
            req.fp = self._feed_fingerprint(req.feed)
        if req.fp and req.fp in self._quarantine:
            # an isolated poison feed resubmitted: shed it at admission
            # instead of letting it fail (and bisect) another batch
            self._quarantine[req.fp] += 1
            self._quarantine.move_to_end(req.fp)
            repeats = self._quarantine[req.fp]
            self._shed_locked("poison_quarantine", now)
            if _monitor.enabled():
                _monitor.counter(
                    "serving_bisect_quarantine_sheds_total",
                    "quarantined poison feeds shed at admission").inc()
            raise Overloaded(
                f"serving: feed fingerprint {req.fp} is quarantined "
                f"(isolated as a poison request; shed {repeats} time(s) "
                f"since)", reason="poison_quarantine")
        if self.config.tenant_fair_share:
            # per-tenant queue quota BEFORE the global depth bound: a hot
            # tenant is shed typed tenant_quota while the queue still has
            # room for everyone else — the under-share tenants keep their
            # SLO. The queued count is an O(queue) scan, deliberately:
            # there is no per-tenant counter to drift out of sync with
            # the queue across shed/sweep/crash-guard mutations, and the
            # queue is bounded by config.queue_depth.
            quota = self._tenant_quota(req.tenant)
            queued = sum(1 for r in self._queue if r.tenant == req.tenant)
            if queued >= quota:
                self._shed_locked("tenant_quota", now)
                # attribute the quota shed in the tenant ledger (the
                # fleet_top share/shed table); lock order engine _lock ->
                # _tenant_lock matches the settle paths
                with self._tenant_lock:
                    t = self._tenant_ledger.setdefault(
                        req.tenant, {"outcomes": {}, "occupancy_s": 0.0})
                    t["quota_sheds"] = t.get("quota_sheds", 0) + 1
                if _monitor.enabled():
                    _monitor.counter(
                        "serving_tenant_quota_sheds_total",
                        "admissions shed by per-tenant queue quota"
                    ).labels(tenant=req.tenant).inc()
                raise Overloaded(
                    f"serving: tenant '{req.tenant}' is over its "
                    f"fair-share queue quota ({queued} >= {quota} of "
                    f"{self.config.queue_depth} slots)",
                    reason="tenant_quota")
        if len(self._queue) >= self.config.queue_depth:
            self._shed_locked("queue_full", now)
            raise Overloaded(
                f"serving: queue full ({len(self._queue)} >= "
                f"{self.config.queue_depth} queued requests)",
                reason="queue_full")
        if self.config.queue_age_s > 0 and self._queue:
            oldest = now - self._queue[0].submitted
            if oldest > self.config.queue_age_s:
                self._shed_locked("queue_age", now)
                raise Overloaded(
                    f"serving: oldest queued request is {oldest:.2f}s old "
                    f"(bound {self.config.queue_age_s:g}s) — the device is "
                    f"not keeping up", reason="queue_age")
        if self._degraded \
                and req.priority < self.config.degraded_min_priority:
            self._shed_locked("priority", now)
            raise Overloaded(
                f"serving: degraded mode sheds priority {req.priority} < "
                f"{self.config.degraded_min_priority}", reason="priority")
        self._update_pressure_locked(now)

    def _shed_locked(self, reason: str, now: float) -> None:
        self._acct["shed"] += 1
        self._record_outcome("shed")
        if _monitor.enabled():
            _monitor.counter(
                "serving_shed_total",
                "requests shed by admission control, by reason").labels(
                reason=reason).inc()
        # a shed IS pressure: it feeds the degradation clock
        self._pressure_since = self._pressure_since or now
        self._calm_since = None
        self._update_pressure_locked(now)

    # -- degradation -----------------------------------------------------
    def _update_pressure_locked(self, now: float) -> None:
        depth = len(self._queue)
        pressured = depth >= max(1, (3 * self.config.queue_depth) // 4)
        if not pressured and self.config.queue_age_s > 0 and self._queue:
            pressured = (now - self._queue[0].submitted
                         > self.config.queue_age_s / 2)
        if pressured:
            self._pressure_since = self._pressure_since or now
            self._calm_since = None
        elif self._pressure_since is not None or self._degraded:
            self._calm_since = self._calm_since or now
            self._pressure_since = None
        if (not self._degraded and self._pressure_since is not None
                and now - self._pressure_since
                >= self.config.degrade_after_s):
            self._degraded = True
            self._cur_max_batch = max(1, self.config.max_batch // 2)
            logger.warning(
                "serving: sustained overload for %.2fs — DEGRADED mode "
                "(max batch %d -> %d; shedding priority < %d)",
                now - self._pressure_since, self.config.max_batch,
                self._cur_max_batch, self.config.degraded_min_priority)
            if _monitor.enabled():
                _monitor.counter("serving_degradations_total",
                                 "entries into degraded mode").inc()
                _monitor.gauge("serving_degraded",
                               "1 while degraded (shrunk batch + priority "
                               "shedding)").set(1)
        elif (self._degraded and self._calm_since is not None
                and now - self._calm_since >= self.config.recover_after_s):
            self._degraded = False
            self._cur_max_batch = self.config.max_batch
            self._calm_since = None
            logger.warning("serving: pressure cleared — restored full "
                           "batch ceiling %d", self.config.max_batch)
            if _monitor.enabled():
                _monitor.gauge("serving_degraded",
                               "1 while degraded (shrunk batch + priority "
                               "shedding)").set(0)

    # -- dispatch thread -------------------------------------------------
    def _dispatch_loop(self) -> None:
        """Crash-guarded shell: whatever kills the inner loop (a bug in
        result slicing, a monitor conflict, the future's double-settle
        guard) must NOT strand callers blocked on futures — every taken
        and queued request still gets a typed terminal outcome, and the
        engine stops admitting instead of queueing into a dead thread."""
        from ..resilience.retry import set_thread_stop_event

        # any retry backoff THIS thread enters wakes when stop() fires
        set_thread_stop_event(self._stop_ev)
        try:
            self._dispatch_forever()
        except BaseException as e:
            logger.exception(
                "serving: dispatch thread DIED (%s) — failing queued and "
                "in-flight requests typed, engine stops admitting",
                type(e).__name__)
            with self._lock:
                self._running = False
                self._stopped = True
                leftovers, self._queue = self._queue, []
                self._gauge_depth_locked()
            for r in (self._current_batch or []):
                if not r.future.done():
                    self._settle_error(
                        r, "rejected_stopped",
                        EngineStopped(f"serving: dispatch thread crashed "
                                      f"mid-batch: {type(e).__name__}: {e}"),
                        dispatched=True)
            for r in leftovers:
                if not r.future.done():
                    self._settle_error(
                        r, "rejected_stopped",
                        EngineStopped(f"serving: dispatch thread crashed: "
                                      f"{type(e).__name__}: {e}"))

    def _dispatch_forever(self) -> None:
        self._current_batch: List[_Request] = []
        while True:
            with self._lock:
                while self._running and not self._queue:
                    # periodic wake even when idle: deadline sweeps and
                    # degradation recovery must not wait for traffic
                    self._work.wait(timeout=0.05)
                    self._sweep_expired_locked(self._now())
                    self._update_pressure_locked(self._now())
                if not self._running and (not self._queue or not self._drain):
                    leftovers, self._queue = self._queue, []
                    self._gauge_depth_locked()
                else:
                    leftovers = None
                    now = self._now()
                    self._sweep_expired_locked(now)
                    self._update_pressure_locked(now)
                    batch = self._take_batch_locked(now)
                    self._dispatched += len(batch)
            if leftovers is not None:
                for r in leftovers:
                    self._settle_error(
                        r, "rejected_stopped",
                        EngineStopped("serving: engine stopped without "
                                      "draining the queue"))
                return
            if batch:
                self._current_batch = batch
                try:
                    self._run_batch(batch)
                finally:
                    self._current_batch = []

    def _sweep_expired_locked(self, now: float) -> None:
        """Expired deadlines get their typed outcome BEFORE wasting a
        batch slot."""
        live = []
        for r in self._queue:
            if r.deadline is not None and r.deadline.expired:
                self._settle_error(
                    r, "deadline_exceeded",
                    DeadlineExceeded(r.deadline.what, r.deadline.budget_s,
                                     r.deadline.elapsed()),
                    locked=True)
            else:
                live.append(r)
        if len(live) != len(self._queue):
            self._queue[:] = live
            self._gauge_depth_locked()

    def _take_batch_locked(self, now: float) -> List[_Request]:
        if not self._queue:
            return []
        # fair share picks the batch ANCHOR (the request guaranteed a
        # slot): the head of the queue normally, the first queued request
        # of the lowest-pass tenant under weighted fair queueing. The
        # rest of the batch still coalesces same-signature requests in
        # FIFO order — fairness decides whose turn it is, not the
        # bucketing.
        anchor = (self._fair_anchor_locked()
                  if self.config.tenant_fair_share else self._queue[0])
        sig = anchor.sig
        cap = self._cur_max_batch
        # the anchor rides even when degradation shrank the ceiling below
        # its row count: dispatched ALONE at its natural bucket — the
        # degraded cap bounds coalescing, it must never strand an
        # admitted request without a terminal outcome
        batch, rows, rest = [anchor], anchor.nrows, []
        for r in self._queue:
            if r is anchor:
                continue
            if r.sig == sig and rows + r.nrows <= cap:
                batch.append(r)
                rows += r.nrows
            else:
                rest.append(r)
        if (rows < cap and self.config.batch_window_s > 0
                and not getattr(self, "_windowed", False)):
            # give the batch exactly one window to fill (the flag stays
            # set through the re-take so it cannot wait twice). Submits
            # notify the condition, so wait in a loop until the window
            # expires or the bucket is full — an early wake must not
            # dispatch a half-filled batch
            self._windowed = True
            try:
                until = now + self.config.batch_window_s
                while True:
                    left = until - self._now()
                    if left <= 0:
                        break
                    self._work.wait(timeout=left)
                    if sum(r.nrows for r in self._queue
                           if r.sig == sig) >= cap:
                        break
                self._sweep_expired_locked(self._now())
                return self._take_batch_locked(self._now())
            finally:
                self._windowed = False
        self._queue[:] = rest
        self._gauge_depth_locked()
        if self.config.tenant_fair_share:
            self._fair_charge_locked(batch)
        return batch

    # -- weighted fair share (docs/SERVING.md "Fleet control loop") ------
    def _tenant_weight(self, tenant: str) -> float:
        return self._tenant_weights.get(tenant, 1.0)

    def _tenant_quota(self, tenant: str) -> int:
        """Queue slots tenant may hold: ``depth * quota_frac * weight``,
        at least 1, at most the whole queue."""
        depth = self.config.queue_depth
        quota = int(depth * self.config.tenant_quota_frac
                    * self._tenant_weight(tenant))
        return max(1, min(depth, quota))

    def _fair_anchor_locked(self) -> "_Request":
        """Stride scheduling (DWRR-equivalent): the next batch is
        anchored on the first queued request of the tenant with the
        smallest pass value. Passes advance by ``rows / weight`` at
        dispatch, so over time each tenant's dispatched rows converge to
        its weight share; a tenant with nothing queued is dropped from
        the table and re-enters at the current minimum pass (no banked
        credit, no starvation)."""
        first: Dict[str, _Request] = {}
        for r in self._queue:
            if r.tenant not in first:
                first[r.tenant] = r
        if len(first) <= 1:
            return self._queue[0]
        for t in list(self._tenant_pass):
            if t not in first:
                del self._tenant_pass[t]
        floor = min(self._tenant_pass.values()) if self._tenant_pass \
            else 0.0
        for t in first:
            self._tenant_pass.setdefault(t, floor)
        best = min(first, key=lambda t: (self._tenant_pass[t],
                                         first[t].seq))
        return first[best]

    def _fair_charge_locked(self, batch: List["_Request"]) -> None:
        for r in batch:
            self._tenant_pass[r.tenant] = (
                self._tenant_pass.get(r.tenant, 0.0)
                + r.nrows / self._tenant_weight(r.tenant))

    def _run_batch(self, batch: List[_Request], depth: int = 0,
                   ctx: Optional[dict] = None) -> None:
        """Execute one coalesced batch. ``depth > 0`` is a bisection
        re-dispatch (``_resolve_failed_batch``): the breaker, the
        ``batch_dispatch`` fault probe and the flight-recorder incident
        belong to the ORIGINAL depth-0 dispatch only — a re-dispatched
        half is already inside one failure's blast-radius accounting.
        ``ctx`` is the depth-0 resolution's shared bisection context
        (poison candidates are deferred into it)."""
        rows = sum(r.nrows for r in batch)
        padded = self._bucket_size(rows)
        sig = batch[0].sig
        bucket = (sig, padded)
        br = None
        if depth == 0:
            br = self._breakers.get(bucket)
            if br is None:
                br = CircuitBreaker(self.config.breaker_threshold,
                                    self.config.breaker_cooldown_s,
                                    name=self._bucket_label(bucket))
                with self._lock:   # health() snapshots the dict concurrently
                    self._breakers[bucket] = br
            verdict = br.allow()
            if verdict == "no":
                for r in batch:
                    self._settle_error(
                        r, "circuit_open",
                        CircuitOpen(
                            f"serving: bucket {br.name} quarantined "
                            f"(state={br.state}, "
                            f"{br.snapshot()['consecutive_failures']} "
                            f"consecutive failures)", bucket=br.name),
                        dispatched=True)
                self._gauge_open_buckets()
                return
        # one batch span (its own trace) linking the member request
        # traces; each request gets a 'serving.dispatch' child under ITS
        # root carrying the batch ids — submit-thread -> dispatch-thread
        # parentage without N-parent spans
        label = self._bucket_label(bucket)
        batch_span = _trace.NOOP_SPAN
        if _trace.enabled():
            batch_span = _trace.root_span(
                "serving.batch", bucket=label, rows=rows, padded=padded,
                requests=len(batch), bisect_depth=depth,
                request_traces=",".join(r.span.trace_id for r in batch))
            for r in batch:
                r.dispatch_span = _trace.start_span(
                    "serving.dispatch", parent=r.span, bucket=label,
                    bisect_depth=depth,
                    batch_trace=batch_span.trace_id,
                    batch_span=batch_span.span_id)
        try:
            if depth == 0:
                _faults.fault_point("batch_dispatch")
            feed = self._pad_feed(batch, rows, padded)
            t0 = time.perf_counter()
            # executor/compile/retry spans nest under the batch span
            with _trace.attach(batch_span):
                outs = self._exe.run(self._program, feed=feed,
                                     fetch_list=self._fetch_names,
                                     scope=self._scope)
            batch_s = time.perf_counter() - t0
        except Exception as e:   # typed per-batch isolation; engine lives
            if br is not None:
                br.record_failure()
                self._gauge_open_buckets()
            if _monitor.enabled():
                _monitor.counter(
                    "serving_batches_total",
                    "dispatched batches by result").labels(
                    result="failed").inc()
            logger.warning(
                "serving: batch of %d request(s) on bucket %s failed at "
                "bisect depth %d (%s: %s)",
                len(batch), label, depth, type(e).__name__, e)
            batch_span.set_attribute("outcome", "failed")
            batch_span.end(error=e)
            self._resolve_failed_batch(batch, e, depth, label, ctx)
            if br is not None and any(m.future.done()
                                      and m.future._error is None
                                      for m in batch):
                # bisection COMPLETED some member on this same bucket:
                # the bucket is demonstrably healthy (one request was
                # poison), so the failure recorded above must not climb
                # the consecutive-failure ladder toward CircuitOpen
                br.record_success()
                self._gauge_open_buckets()
            return
        if br is not None:
            br.record_success()
            self._gauge_open_buckets()
        batch_span.set_attribute("outcome", "ok")
        batch_span.end()
        _monitor.observe_serving_cost(self._program, padded, batch_s,
                                      label)
        if _monitor.enabled():
            _monitor.counter("serving_batches_total",
                             "dispatched batches by result").labels(
                result="ok").inc()
            _monitor.histogram(
                "serving_batch_occupancy",
                "real rows / padded bucket rows per dispatched batch",
                buckets=OCCUPANCY_BUCKETS).observe(rows / padded)
            _monitor.histogram(
                "serving_batch_seconds",
                "wall time of one dispatched serving batch").observe(
                batch_s)
        self._distribute(batch, outs, padded)

    def _resolve_failed_batch(self, batch: List[_Request],
                              cause: BaseException, depth: int,
                              label: str,
                              ctx: Optional[dict] = None) -> None:
        """Blast-radius resolution for one failed batch: bisect when the
        failure is state-safe and the depth budget allows (innocents
        complete, the isolated culprit settles typed
        :class:`PoisonRequest` and is quarantined), otherwise fail every
        member typed :class:`BatchFailed`. Every member reaches exactly
        one terminal outcome on every path; per-member deadlines stay
        enforced (an expired member settles ``DeadlineExceeded`` instead
        of riding a re-dispatch).

        Poison candidates are DEFERRED into the depth-0 resolution
        context and finalized only once the whole bisection completed:
        the poison classification requires a completed batch-mate
        witness (or a mate-less singleton batch) — when EVERY member of
        a batch fails, the bucket is broken, not the requests, and
        quarantining innocent feeds would shed legitimate resubmissions
        at admission."""
        top = ctx is None
        if top:
            ctx = {"poison": []}
        live: List[_Request] = []
        for r in batch:
            if r.deadline is not None and r.deadline.expired:
                self._settle_error(
                    r, "deadline_exceeded",
                    DeadlineExceeded(r.deadline.what, r.deadline.budget_s,
                                     r.deadline.elapsed()),
                    dispatched=True)
            else:
                live.append(r)
        max_depth = self.config.bisect_depth
        bisectable = max_depth > 0 and self._bisect_safe(cause)
        if live and bisectable and len(live) == 1 and depth > 0:
            # re-dispatched without batch mates and still failing: a
            # culprit CANDIDATE — classified at the top of the recursion
            ctx["poison"].append((live[0], cause))
        elif live and bisectable and depth < max_depth:
            # a singleton at depth 0 re-dispatches SOLO once (absorbing a
            # transient and confirming a culprit); larger batches split
            mid = max(1, (len(live) + 1) // 2)
            halves = [live[:mid], live[mid:]]
            if _monitor.enabled():
                _monitor.counter(
                    "serving_bisect_splits_total",
                    "failed batches re-dispatched as bisected halves"
                ).inc()
            logger.warning(
                "serving: bisecting failed batch of %d request(s) on "
                "bucket %s (depth %d -> %d): %s: %s",
                len(live), label, depth, depth + 1,
                type(cause).__name__, cause)
            for r in live:
                # the old dispatch child closes here; the re-dispatch
                # opens a fresh one under the same request root
                if r.dispatch_span:
                    r.dispatch_span.set_attribute("outcome", "bisect")
                    r.dispatch_span.end()
                    r.dispatch_span = _trace.NOOP_SPAN
            for half in halves:
                if half:
                    self._run_batch(half, depth=depth + 1, ctx=ctx)
        elif live:
            self._fail_members(live, cause, label, depth)
        if top and ctx["poison"]:
            self._finalize_poison(batch, ctx["poison"], label)

    def _finalize_poison(self, batch: List[_Request], candidates,
                         label: str) -> None:
        """Classify the deferred culprit candidates of one depth-0
        resolution. A candidate is poison only with a completed-mate
        WITNESS (some other member of the original batch succeeded once
        the candidate was out) or when the original batch was a
        mate-less singleton; with no witness, every member failed — a
        broken bucket, settled :class:`BatchFailed` (and counted by the
        breaker's consecutive-failure ladder), never a quarantined
        innocent."""
        witness = any(r.future.done() and r.future._error is None
                      for r in batch)
        if witness or len(batch) == 1:
            for r, cause in candidates:
                self._settle_poison(r, cause, label)
            return
        logger.warning(
            "serving: refusing poison classification on bucket %s — all "
            "%d member(s) failed (no completed-mate witness); the bucket "
            "is broken, not one request", label, len(batch))
        self._fail_members([r for r, _ in candidates],
                           candidates[0][1], label, depth=0)

    def _fail_members(self, live: List[_Request], cause: BaseException,
                      label: str, depth: int) -> None:
        for r in live:
            # one instance per future: concurrent result() raises would
            # otherwise interleave __traceback__ on a shared exception
            err = BatchFailed(
                f"serving: batch failed on bucket {label}: "
                f"{type(cause).__name__}: {cause}")
            err.__cause__ = cause
            self._settle_error(r, "failed", err, dispatched=True)
        if live:
            # flight recorder: the incident ships with the failed
            # requests' full span chains (settled above, so the terminal
            # outcomes are already in the ring). Recorded at ANY depth —
            # this is the terminal resolution of these requests, and a
            # sub-batch that dies mid-bisection must not lose its dump
            _trace.record_incident(
                "batch_failed", error=cause, context=live[0].span,
                detail=f"bucket {label}, {len(live)} request(s), "
                       f"bisect depth {depth}")

    def _settle_poison(self, r: _Request, cause: BaseException,
                       label: str) -> None:
        fp = r.fp or self._feed_fingerprint(r.feed)
        err = PoisonRequest(
            f"serving: request #{r.seq} isolated by bisection as the "
            f"poison member of a failing batch on bucket {label} "
            f"({type(cause).__name__}: {cause}); feed fingerprint {fp} "
            f"quarantined", fingerprint=fp)
        err.__cause__ = cause
        with self._lock:
            self._quarantine[fp] = self._quarantine.get(fp, 0)
            self._quarantine.move_to_end(fp)
            while len(self._quarantine) > max(1,
                                              self.config.bisect_quarantine):
                self._quarantine.popitem(last=False)
            qsize = len(self._quarantine)
        logger.warning("serving: POISON request #%d isolated on bucket "
                       "%s — fingerprint %s quarantined (%s: %s)",
                       r.seq, label, fp, type(cause).__name__, cause)
        if _monitor.enabled():
            _monitor.counter(
                "serving_bisect_poison_total",
                "poison requests isolated by batch bisection").inc()
            _monitor.gauge(
                "serving_bisect_quarantine_size",
                "poison feed fingerprints currently quarantined").set(qsize)
        self._settle_error(r, "poisoned", err, dispatched=True)
        _trace.record_incident(
            "poison_request", error=err, context=r.span,
            detail=f"bucket {label}, fingerprint {fp}")

    @staticmethod
    def _bisect_safe(e: BaseException) -> bool:
        """May a failed batch be re-dispatched in halves? NO when the
        failure may have corrupted device state: a watchdog-broken hang
        or a lost device leaves the executor in an unknown state, and an
        error naming consumed/deleted donated buffers means re-running
        would read through freed storage — those fail the WHOLE batch
        (the pre-bisection contract). Walks the cause chain."""
        try:
            from ..resilience.distributed import WatchdogTimeout
        except ImportError:                      # pragma: no cover
            WatchdogTimeout = ()
        try:
            from ..resilience.elastic import DeviceLostError
        except ImportError:                      # pragma: no cover
            DeviceLostError = ()
        seen = set()
        cur: Optional[BaseException] = e
        while cur is not None and id(cur) not in seen:
            seen.add(id(cur))
            if isinstance(cur, (WatchdogTimeout, DeviceLostError)):
                return False
            msg = str(cur).lower()
            if "donated" in msg or "deleted" in msg:
                return False
            cur = cur.__cause__ or cur.__context__
        return True

    @staticmethod
    def _feed_fingerprint(feed: Dict[str, np.ndarray]) -> str:
        """Content hash of one request's feed — the quarantine key. Bit
        sensitivity is deliberate: the SAME poison bytes are shed, a
        perturbed resubmission gets a fresh chance."""
        import hashlib

        h = hashlib.sha256()
        for n in sorted(feed):
            a = np.ascontiguousarray(feed[n])
            h.update(n.encode("utf-8"))
            h.update(str(a.dtype).encode("ascii"))
            h.update(repr(a.shape).encode("ascii"))
            h.update(a.tobytes())
        return h.hexdigest()[:32]

    def _distribute(self, batch, outs, padded) -> None:
        now = self._now()
        offset = 0
        for r in batch:
            res = []
            for o in outs:
                a = np.asarray(o)
                if a.ndim and a.shape[0] == padded:
                    res.append(a[offset:offset + r.nrows])
                else:
                    # batch-invariant fetch (scalar/aggregate): every
                    # request gets the full value
                    res.append(a)
            offset += r.nrows
            if r.deadline is not None and r.deadline.expired:
                # the batch outran the request's budget (e.g. a cold
                # bucket compile): the documented contract is a typed
                # DeadlineExceeded, never a stale late response
                self._settle_error(
                    r, "deadline_exceeded",
                    DeadlineExceeded(r.deadline.what, r.deadline.budget_s,
                                     r.deadline.elapsed()),
                    dispatched=True)
                continue
            latency = now - r.submitted
            with self._lock:
                self._acct["completed"] += 1
                self._dispatched -= 1
            self._record_outcome("completed")
            self._finish_request(r, "completed")
            if _monitor.enabled():
                # trace exemplar: with the telemetry plane on, the
                # observation carries this request's trace id into the
                # bounded per-bucket exemplar ring (JSON metrics form
                # only); off = no allocation, the plain observe() path
                ex = r.span.trace_id \
                    if _monitor.telemetry_enabled() else None
                _monitor.histogram(
                    "serving_request_latency_seconds",
                    "submit-to-response latency of completed requests "
                    "(p50/p99 in the snapshot)").observe(
                    latency, exemplar=ex or None)
            r.future._settle(result=res)

    # -- helpers ---------------------------------------------------------
    def _bucket_size(self, rows: int) -> int:
        p = 1
        while p < rows:
            p <<= 1
        return min(p, self.config.max_batch)

    @staticmethod
    def _bucket_label(bucket) -> str:
        sig, padded = bucket
        shapes = ",".join(f"{n}[{'x'.join(map(str, s))}:{d}]"
                          for n, s, d in sig)
        return f"b{padded}({shapes})"

    def _pad_feed(self, batch, rows, padded) -> Dict[str, np.ndarray]:
        feed = {}
        for n in self._feed_names:
            parts = [r.feed[n] for r in batch]
            if padded > rows:
                pad = np.zeros((padded - rows,) + parts[0].shape[1:],
                               dtype=parts[0].dtype)
                parts = parts + [pad]
            feed[n] = np.concatenate(parts, axis=0) if len(parts) > 1 \
                else parts[0]
        return feed

    def _finish_request(self, r: _Request, outcome: str,
                        err: Optional[BaseException] = None) -> None:
        """Terminal-outcome bookkeeping shared by every settle path:
        close the dispatch child (if one is open) and the request root
        span with the typed outcome, stamp the trace id onto the error
        and the accounting's recent-outcomes ring. Idempotent on the
        span side (``Span.end`` closes once)."""
        if err is not None and isinstance(err, (ServingError,
                                                DeadlineExceeded)):
            err.trace_id = r.span.trace_id
        if r.dispatch_span:
            r.dispatch_span.end(error=err)
        if r.span:
            r.span.set_attribute("outcome", outcome)
            r.span.end(status="ok" if err is None else "error", error=err)
        # bounded deque append is GIL-atomic; callers may or may not hold
        # the engine lock
        self._recent_outcomes.append(
            {"seq": r.seq, "outcome": outcome,
             "trace_id": r.span.trace_id})
        # SLO + tenant accounting, once per terminal outcome (this method
        # is the single chokepoint every settle path funnels through).
        # Both stores are leaf-locked, never the engine lock.
        elapsed = self._now() - r.submitted
        completed = outcome == "completed"
        self._slo.observe(r.priority, elapsed if completed else None,
                          error=not completed)
        with self._tenant_lock:
            t = self._tenant_ledger.get(r.tenant)
            if t is None:
                t = self._tenant_ledger[r.tenant] = {"outcomes": {},
                                                     "occupancy_s": 0.0}
            t["outcomes"][outcome] = t["outcomes"].get(outcome, 0) + 1
            t["occupancy_s"] += elapsed
        if _monitor.enabled():
            _monitor.counter(
                "fleet_tenant_requests_total",
                "request terminal outcomes by accounting tenant "
                "(sums exactly to serving_requests_total)").labels(
                tenant=r.tenant, outcome=outcome).inc()
            _monitor.counter(
                "fleet_tenant_occupancy_seconds",
                "summed submit-to-settle seconds by tenant (time each "
                "tenant's requests occupied the engine)").labels(
                tenant=r.tenant).inc(elapsed)

    def _settle_error(self, r: _Request, key: str, err: BaseException,
                      locked: bool = False, dispatched: bool = False) -> None:
        """``dispatched``: the request had been taken off the queue (its
        batch executed), so the in-flight count must drop with it."""
        if locked:
            self._acct[key] += 1
            if dispatched:
                self._dispatched -= 1
        else:
            with self._lock:
                self._acct[key] += 1
                if dispatched:
                    self._dispatched -= 1
        self._record_outcome(key)
        self._finish_request(r, key, err)
        r.future._settle(error=err)

    def _account(self, key: str) -> None:
        with self._lock:
            self._acct[key] += 1
        self._record_outcome(key)

    @staticmethod
    def _record_outcome(outcome: str) -> None:
        if _monitor.enabled():
            _monitor.counter(
                "serving_requests_total",
                "request terminal outcomes (exactly one per submitted "
                "request)").labels(outcome=outcome).inc()
            if outcome == "deadline_exceeded":
                _monitor.counter(
                    "serving_deadline_exceeded_total",
                    "requests that expired before a response").inc()

    def _gauge_depth_locked(self) -> None:
        if _monitor.enabled():
            _monitor.gauge("serving_queue_depth",
                           "requests waiting for dispatch").set(
                len(self._queue))

    def _gauge_open_buckets(self) -> None:
        if _monitor.enabled():
            with self._lock:
                breakers = list(self._breakers.values())
            _monitor.gauge(
                "serving_breaker_open_buckets",
                "shape buckets currently quarantined").set(
                sum(1 for b in breakers if b.state != "closed"))

    # -- observability ---------------------------------------------------
    def warm_up(self, batch_sizes: Optional[Sequence[int]] = None) -> int:
        """Pre-compile the power-of-two buckets with zero feeds built
        from the program's declared var shapes, so first real traffic
        never pays a compile. Returns the number of buckets compiled.
        Call before ``start()`` (or any time — the step cache absorbs
        duplicates)."""
        from ..core.types import np_dtype

        if batch_sizes is None:
            batch_sizes, b = [], 1
            while b < self.config.max_batch:
                batch_sizes.append(b)
                b <<= 1
            # max_batch itself is always a reachable bucket (_bucket_size
            # caps there), even when it is not a power of two
            batch_sizes.append(self.config.max_batch)
        blk = self._program.global_block
        for b in batch_sizes:
            feed = {}
            for n in self._feed_names:
                v = blk.var(n)
                tail = tuple(int(d) for d in v.shape[1:])
                feed[n] = np.zeros((int(b),) + tail, dtype=np_dtype(v.dtype))
            self._exe.run(self._program, feed=feed,
                          fetch_list=self._fetch_names, scope=self._scope)
        return len(batch_sizes)

    def accounting(self) -> dict:
        """Exact request accounting: ``submitted`` equals the sum of all
        terminal outcomes plus ``pending``. The load gate's invariant."""
        with self._lock:
            acct = dict(self._acct)
            # pending = queued + taken-but-unsettled (a batch mid-flight):
            # the invariant must hold at ANY instant, not just at idle
            acct["pending"] = len(self._queue) + self._dispatched
        terminal = sum(v for k, v in acct.items()
                       if k not in ("submitted", "pending"))
        acct["accounted"] = terminal + acct["pending"]
        acct["exact"] = acct["accounted"] == acct["submitted"]
        # the last N terminal outcomes with their trace ids: a failed
        # gate leg names the exact requests (FLAGS_trace off => ids "")
        acct["recent_outcomes"] = list(self._recent_outcomes)
        return acct

    def tenant_accounting(self) -> dict:
        """Per-tenant terminal-outcome ledger: ``{tenant: {"outcomes":
        {outcome: n}, "occupancy_s": float}}``. At quiescence the outcome
        counts sum exactly to ``accounting()``'s terminal counts — the
        fleet CI gate's tenant-reconciliation invariant."""
        with self._tenant_lock:
            out = {t: {"outcomes": dict(v["outcomes"]),
                       "occupancy_s": v["occupancy_s"],
                       "quota_sheds": v.get("quota_sheds", 0)}
                   for t, v in self._tenant_ledger.items()}
        if self.config.tenant_fair_share:
            # additive keys (documented minor change): the tenant's
            # configured share so the shed counts are auditable against
            # the policy that produced them
            for t, rec in out.items():
                rec["weight"] = self._tenant_weight(t)
                rec["quota"] = self._tenant_quota(t)
        return out

    def slo_state(self) -> dict:
        """The SLO burn tracker's serialized state (the health payload's
        ``"slo"`` value); refreshes the ``slo_burn_*`` gauges."""
        return self._slo.state()

    def health(self) -> dict:
        """Liveness/pressure snapshot. This payload is the fleet tier's
        WIRE CONTRACT (``/healthz`` serves it verbatim and the router's
        load-aware dispatch reads it): the key set is versioned and
        frozen as :data:`HEALTH_SCHEMA_KEYS` — see docs/SERVING.md
        "Health probe schema" before changing anything here."""
        with self._lock:
            depth = len(self._queue)
            degraded = self._degraded
            running = self._running
            cur_max = self._cur_max_batch
            breakers = list(self._breakers.values())
        open_buckets = [b.snapshot() for b in breakers
                        if b.state != "closed"]
        status = ("stopped" if not running
                  else "degraded" if degraded or open_buckets else "ok")
        return {"schema_version": HEALTH_SCHEMA_VERSION,
                "status": status, "ready": self.ready(),
                "queue_depth": depth,
                "queue_limit": self.config.queue_depth,
                "degraded": degraded, "current_max_batch": cur_max,
                "open_buckets": open_buckets,
                "accounting": self.accounting(),
                "slo": self._slo.state()}

    def ready(self) -> bool:
        """Readiness probe: accepting traffic and the dispatcher is
        alive."""
        with self._lock:
            running = self._running
        return bool(running and self._thread is not None
                    and self._thread.is_alive())
