"""paddle_tpu.serving — production inference serving with continuous
batching, admission control, deadlines and graceful degradation.

The online path the ROADMAP's "millions of users" north star needs
(item 1): the training stack already survives crashes, hangs and bad
batches (``paddle_tpu.resilience``, PRs 4/6); this package gives the
SAME guarantees to request traffic. The contract is one sentence: *every
submitted request reaches exactly one terminal outcome — a response or a
typed rejection — even under overload, compile failures and injected
faults.* ``tools/load_check.py`` proves it in CI.

Quick start::

    from paddle_tpu import serving

    infer = main.clone(for_test=True)          # params already in `scope`
    engine = serving.ServingEngine(infer, feed_names=["img", "label"],
                                   fetch_list=[logits], scope=scope)
    engine.warm_up()                           # pre-compile the buckets
    with engine:                               # start()/stop(drain=True)
        fut = engine.submit({"img": x, "label": y}, deadline_s=0.5)
        logits_rows = fut.result()             # or typed ServingError

Architecture, flag table and failure modes: docs/SERVING.md. SLO metrics
(latency p50/p99, queue depth, occupancy, shed/deadline/breaker
counters): docs/OBSERVABILITY.md.
"""
from __future__ import annotations

from ..resilience.deadline import Deadline, DeadlineExceeded
from .breaker import CircuitBreaker
from .engine import (HEALTH_SCHEMA_KEYS, HEALTH_SCHEMA_VERSION,
                     BatchFailed, CircuitOpen, EngineStopped, Overloaded,
                     PoisonRequest, ServingConfig, ServingEngine,
                     ServingError, ServingFuture)
from .generate import GenerationConfig, GenerativeEngine
from . import fleet

__all__ = [
    "ServingEngine", "ServingConfig", "ServingFuture", "CircuitBreaker",
    "Deadline", "GenerativeEngine", "GenerationConfig",
    # typed terminal outcomes
    "ServingError", "Overloaded", "CircuitOpen", "BatchFailed",
    "PoisonRequest", "EngineStopped", "DeadlineExceeded",
    # the frozen health()/ready() wire contract (docs/SERVING.md)
    "HEALTH_SCHEMA_VERSION", "HEALTH_SCHEMA_KEYS",
    # the network tier (front-end, router, wire schema, replica worker)
    "fleet",
]
