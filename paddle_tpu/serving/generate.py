"""Generative serving: prefill/decode split scheduling over a GPT model.

``GenerativeEngine`` extends :class:`~paddle_tpu.serving.engine.ServingEngine`
with the autoregressive workload class (ROADMAP item 1): requests are token
prompts, responses are token streams. The engine owns a fixed set of
**batch slots** — one shared KV-page bucket per slot batch — and splits
work into the two phases of ``models/gpt.py``:

* **prefill** — queued requests are admitted into free slots at decode-
  chunk boundaries and prefilled as one slot-masked batch per prompt
  bucket (padded to the bucket length). The prefill writes the slot's KV
  pages, merges the slot's generation state, and produces the request's
  FIRST token — streamed immediately.
* **decode** — every active slot advances ``decode_chunk`` tokens per
  dispatch as ONE ``run_chained`` scan (the paged KV caches ride the scan
  carry, donation-proven, updated in place; sampling runs in-program so
  no host round-trip separates tokens). Sequences sit at *different
  positions* inside one batch — position is data, not shape, so every
  chunk reuses one executable per (phase, bucket). The
  ``serving_decode_recompiles_total`` guard turns any violation (a shape
  leaking into a cache key as KV grows) into a counted, logged event and
  a CI-gated metric.

ISSUE 20 adds two composable phases on the same slot/bucket discipline:

* **prefix reuse + chunked prefill** — admission first matches the
  prompt against the content-hash :class:`~.prefix_cache.PrefixCache`;
  matched whole pages are COPIED into the slot's KV rows and only the
  suffix is prefilled, one ``prefill_chunk``-token slot-masked slice per
  scheduler iteration, interleaved with the resident decode chunks (the
  same path admits prompts longer than the largest bucket). The final
  slice samples the first token in-program and flips the slot's decode
  gate (``gpt_gen_active``); completed prefills publish their pages.
* **speculative decoding** (``GenerationConfig.speculative``) — each
  round a host-side draft (prompt-lookup n-gram by default, swappable
  via ``engine.draft_fn``) proposes ``spec_k - 1`` tokens and the target
  verifies the whole chunk in ONE dispatch; ``spec_accept`` commits the
  longest agreeing prefix + bonus token in-program. Greedy speculative
  output is bit-exact vs non-speculative decode — the verify scores each
  position with the identical model and context, so acceptance never
  changes WHAT is generated, only how many dispatches it takes.

Contract (inherited, unchanged): every submitted request reaches EXACTLY
ONE terminal outcome. Streamed tokens are partial results, not outcomes —
a request that expires mid-stream settles ``DeadlineExceeded`` (typed)
with its partial tokens still readable from the future. Deadlines apply
per token: they are re-checked before every prefill and after every
decode chunk, so an expired stream stops within ``decode_chunk`` tokens.

Failure isolation: an injected ``batch_dispatch`` fault (the chaos gate's
kill-one-batch leg) fails exactly the streams in that dispatch, typed
``BatchFailed``, and the engine keeps serving. A REAL executor failure
mid-dispatch may have consumed donated state buffers, so it additionally
fails every resident stream typed and resets the generation state —
never a silent wrong-token continuation.
"""
from __future__ import annotations

import dataclasses
import logging
import time
from collections import defaultdict
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import monitor as _monitor
from .. import trace as _trace
from ..core.types import np_dtype
from ..resilience import faults as _faults
from ..resilience.deadline import Deadline, DeadlineExceeded
from .engine import (DEFAULT_TENANT, BatchFailed, EngineStopped,
                     ServingConfig, ServingEngine, ServingFuture, _Request)

__all__ = ["GenerationConfig", "GenerativeEngine"]

logger = logging.getLogger("paddle_tpu.serving")


@dataclasses.dataclass
class GenerationConfig:
    """Generative-scheduling knobs (the serving half; model geometry —
    slots, pages, buckets — lives on the ``build_gpt_generative`` dict)."""

    decode_chunk: int = 4          # tokens per chained decode dispatch;
    # also the deadline-enforcement granularity
    max_new_tokens_default: int = 16
    eos_id: int = -1               # < 0: no stop token
    # -- prefix-reuse KV cache (ISSUE 20, tentpole leg a) ----------------
    prefix_cache: bool = True      # content-hash prompt pages, share them
    prefix_cache_pages: int = 64   # LRU bound on stored pages
    # -- chunked prefill -------------------------------------------------
    chunked_prefill: bool = True   # admit long/cold prompts slice by
    # slice between decode chunks instead of one monolithic prefill
    # -- speculative decoding (tentpole leg b) ---------------------------
    speculative: bool = False      # draft k tokens, verify in one dispatch

    def resolve(self) -> "GenerationConfig":
        if self.decode_chunk < 1:
            raise ValueError(f"generation: decode_chunk must be >= 1, got "
                             f"{self.decode_chunk}")
        if self.max_new_tokens_default < 1:
            raise ValueError(f"generation: max_new_tokens_default must be "
                             f">= 1, got {self.max_new_tokens_default}")
        if self.prefix_cache_pages < 1:
            raise ValueError(f"generation: prefix_cache_pages must be >= 1, "
                             f"got {self.prefix_cache_pages}")
        return self


@dataclasses.dataclass
class _GenRequest(_Request):
    prompt: np.ndarray = None      # [L] int64
    bucket: int = 0                # prompt bucket (0: chunked-only admit)
    max_new: int = 1
    slot: int = -1                 # assigned batch slot, -1 while queued
    emitted: int = 0
    out_tokens: List[int] = dataclasses.field(default_factory=list)
    # chunked-prefill / prefix-reuse bookkeeping (dispatcher thread only)
    chunked: bool = False          # admitted via chunk slices
    prefilled: bool = False        # decode-eligible (prefill complete)
    prefix_rows: int = 0           # KV rows copied in from the prefix cache
    next_off: int = 0              # next prompt offset to prefill


class GenerativeEngine(ServingEngine):
    """See module docstring. ``model`` is a ``build_gpt_generative`` dict;
    parameters must already be initialized in ``scope`` (run the model's
    startup program first). Generation state (tokens/positions/KV pages)
    is planted and reset by the engine itself."""

    def __init__(self, model: dict, scope=None, place=None, executor=None,
                 config: Optional[ServingConfig] = None,
                 gen_config: Optional[GenerationConfig] = None):
        decode = model["decode"]
        super().__init__(decode["main"], feed_names=[],
                         fetch_list=[decode["next_token"]],
                         scope=scope, place=place, executor=executor,
                         config=config)
        self._model = model
        self.gen_config = (gen_config or GenerationConfig()).resolve()
        self._slots: List[Optional[_GenRequest]] = \
            [None] * int(model["batch_slots"])
        self._max_seq = int(model["max_seq"])
        self._page_size = int(model["page_size"])
        self._buckets = tuple(model["prompt_buckets"])
        # recompile guard: (phase, bucket) -> True once its executable
        # exists; any LATER cache growth on the same key is a recompile
        self._compiled_buckets: Dict[tuple, bool] = {}
        self.decode_recompiles = 0
        # chunked prefill + speculative verify programs (absent on model
        # dicts from before ISSUE 20 — every new path degrades to the
        # bucket-prefill / plain-decode behaviour)
        self._chunk = model.get("chunk")
        self._verify = model.get("verify")
        self._prefill_chunk = int(model.get("prefill_chunk") or
                                  self._page_size)
        self._spec_k = int(model.get("spec_k") or 0)
        self._cache_names = sorted(
            (n, "gpt_kv_v_" + n[len("gpt_kv_k_"):])
            for n in model["state_vars"] if n.startswith("gpt_kv_k_"))
        gc = self.gen_config
        self._prefix_cache = None
        if gc.prefix_cache and self._chunk is not None:
            from .prefix_cache import PrefixCache
            self._prefix_cache = PrefixCache(
                self._page_size, capacity_pages=gc.prefix_cache_pages)
        self._speculative = bool(
            gc.speculative and self._verify is not None and self._spec_k >= 2)
        # host-side draft proposer for speculative decoding: callable
        # (history_tokens: np.ndarray, n: int) -> n proposed tokens.
        # Default: prompt-lookup n-gram (see _ngram_draft). Swappable for
        # tests and for a real draft model.
        self.draft_fn = None
        self.prefill_chunks = 0    # chunk slices dispatched (per request)
        self.spec_chunks = 0       # verify dispatches
        self.spec_accepted = 0     # draft tokens accepted in total

    # -- state lifecycle -------------------------------------------------
    def reset_generation_state(self) -> None:
        """Plant zeroed generation state (tokens, positions, KV pages) in
        the scope. Called at warm-up/start and after a real mid-dispatch
        failure (consumed donated buffers are never reused)."""
        for name, (shape, dt) in self._model["state_vars"].items():
            self._scope.set_var(name, np.zeros(shape, np_dtype(dt)))

    def _ensure_state(self) -> None:
        for name in self._model["state_vars"]:
            if self._scope.find_var(name) is None:
                self.reset_generation_state()
                return

    def start(self) -> "GenerativeEngine":
        self._ensure_state()
        super().start()
        return self

    def warm_up(self, batch_sizes=None) -> int:
        """Compile every (phase, bucket) executable before traffic: each
        prefill bucket with an all-zero slot mask (no slot is touched) and
        one decode chunk on scratch state. Seeds the recompile guard —
        after warm-up, steady-state decode must never compile again.

        Unlike the base engine's stateless warm-up, this one RESETS the
        generation state and dispatches on the caller thread, so it must
        run before ``start()``: on a running engine it would zero resident
        streams' caches mid-generation while racing the dispatch thread —
        refused loudly instead."""
        with self._lock:
            if self._running:
                raise RuntimeError(
                    "serving: GenerativeEngine.warm_up resets the "
                    "generation state and cannot run on a started engine "
                    "(resident streams would silently decode from zeroed "
                    "caches); call it before start()")
        self.reset_generation_state()
        compiled = 0
        for bucket in self._buckets:
            net = self._model["prefill"][bucket]
            feed = self._prefill_feed(bucket, [])
            self._exe.run(net["main"], feed=feed,
                          fetch_list=[net["first_token"].name],
                          scope=self._scope)
            self._note_compiles("prefill", bucket, net["main"])
            compiled += 1
        self._exe.run_chained(self._program, feed={},
                              fetch_list=self._fetch_names,
                              steps=self.gen_config.decode_chunk,
                              scope=self._scope)
        self._note_compiles("decode", len(self._slots), self._program)
        compiled += 1
        if self._use_chunked():
            net = self._chunk
            self._exe.run(net["main"], feed=self._chunk_feed([]),
                          fetch_list=[net["first_token"].name],
                          scope=self._scope)
            self._note_compiles("chunk", self._prefill_chunk, net["main"])
            compiled += 1
        if self._speculative:
            net = self._verify
            self._exe.run(net["main"], feed=self._verify_feed([]),
                          fetch_list=[net["accept_len"].name,
                                      net["sampled"].name],
                          scope=self._scope)
            self._note_compiles("verify", self._spec_k, net["main"])
            compiled += 1
        self.reset_generation_state()
        return compiled

    def _use_chunked(self) -> bool:
        """Chunked prefill is live when the model ships a chunk program
        and either admission leg needs it (long-prompt slicing or the
        prefix cache's suffix prefill)."""
        return self._chunk is not None and (
            self.gen_config.chunked_prefill or self._prefix_cache is not None)

    # -- submission ------------------------------------------------------
    def submit(self, prompt, *, max_new_tokens: Optional[int] = None,
               priority: int = 0, deadline_s: Optional[float] = None,
               trace_parent=None,
               tenant: Optional[str] = None) -> ServingFuture:
        """Admit one generation request (any thread). ``prompt`` is a 1-D
        int token array (a ``[1, L]`` row is accepted); the returned
        future STREAMS tokens (``ServingFuture.stream()``) and settles
        exactly once with the full token array or a typed error.
        ``trace_parent`` parents the request root span and ``tenant``
        attributes the request in the per-tenant ledger (fleet wire
        propagation — see ``ServingEngine.submit``)."""
        req = self._build_gen_request(prompt, max_new_tokens, priority,
                                      deadline_s, trace_parent, tenant)
        sub = _trace.start_span("serving.submit", parent=req.span,
                                priority=req.priority,
                                prompt_len=len(req.prompt))
        # the base engine's shared admission sequence: accounting, the
        # enqueue fault point, typed rejections, the dispatcher wake
        return self._admit_and_enqueue(req, sub)

    def _build_gen_request(self, prompt, max_new_tokens, priority,
                           deadline_s, trace_parent=None,
                           tenant=None) -> _GenRequest:
        prompt = np.asarray(prompt)
        if prompt.ndim == 2 and prompt.shape[0] == 1:
            prompt = prompt[0]
        if prompt.ndim != 1 or prompt.size == 0:
            raise ValueError(
                f"serving: prompt must be a non-empty 1-D token array, "
                f"got shape {prompt.shape}")
        prompt = prompt.astype(np.int64)
        L = int(prompt.shape[0])
        bucket = next((b for b in self._buckets if b >= L), None)
        chunked = False
        if bucket is None:
            # past the largest bucket: chunked prefill admits it slice by
            # slice (no bucket executable is ever built for this length)
            if not (self._chunk is not None
                    and self.gen_config.chunked_prefill):
                raise ValueError(
                    f"serving: prompt length {L} exceeds the largest "
                    f"prompt bucket {max(self._buckets)}; split or "
                    f"truncate the prompt (or enable chunked_prefill)")
            bucket, chunked = 0, True
        max_new = int(max_new_tokens
                      if max_new_tokens is not None
                      else self.gen_config.max_new_tokens_default)
        if max_new < 1:
            raise ValueError(f"serving: max_new_tokens must be >= 1, got "
                             f"{max_new}")
        if L + max_new > self._max_seq:
            raise ValueError(
                f"serving: prompt ({L}) + max_new_tokens ({max_new}) "
                f"exceeds the KV capacity max_seq {self._max_seq}")
        budget = self.config.deadline_s if deadline_s is None else deadline_s
        seq = next(ServingEngine._seq)
        dl = Deadline(budget, what=f"serving generation #{seq}") \
            if budget and budget > 0 else None
        tenant = str(tenant).strip() if tenant is not None else ""
        req = _GenRequest(seq=seq, feed={}, nrows=1,
                          sig=("gen", bucket or "chunk"),
                          priority=int(priority), deadline=dl,
                          submitted=self._now(), future=ServingFuture(),
                          tenant=tenant or DEFAULT_TENANT,
                          prompt=prompt, bucket=bucket, max_new=max_new,
                          chunked=chunked)
        req.span = self._request_root(trace_parent, seq=seq,
                                      prompt_len=L, max_new=max_new,
                                      priority=int(priority))
        req.future.trace_id = req.span.trace_id
        return req

    # -- scheduler -------------------------------------------------------
    def _dispatch_forever(self) -> None:
        self._current_batch = []
        while True:
            with self._lock:
                while (self._running and not self._queue
                       and not any(r is not None for r in self._slots)):
                    self._work.wait(timeout=0.05)
                    self._sweep_expired_locked(self._now())
                    self._update_pressure_locked(self._now())
                active = [r for r in self._slots if r is not None]
                stopping = not self._running and (
                    not self._drain or (not self._queue and not active))
                if stopping:
                    leftovers, self._queue = self._queue, []
                    self._slots = [None] * len(self._slots)
                    self._gauge_depth_locked()
                else:
                    now = self._now()
                    self._sweep_expired_locked(now)
                    self._update_pressure_locked(now)
                    newcomers = self._refill_locked()
            if stopping:
                for r in leftovers + active:
                    if not r.future.done():
                        self._settle_error(
                            r, "rejected_stopped",
                            EngineStopped("serving: engine stopped without "
                                          "draining"),
                            dispatched=(r in active))
                self._current_batch = []
                return
            # the crash guard settles every RESIDENT request, not just the
            # ones inside one dispatch
            self._current_batch = [r for r in self._slots if r is not None]
            if newcomers:
                self._run_prefill(self._admit_newcomers(newcomers))
                self._current_batch = [r for r in self._slots
                                       if r is not None]
            # one chunk slice per pending chunked request per iteration,
            # INTERLEAVED with the resident decode chunk below — a long
            # cold prompt never stalls the decoders
            if any(r is not None and r.chunked and not r.prefilled
                   for r in self._slots):
                self._run_chunk_slices()
                self._current_batch = [r for r in self._slots
                                       if r is not None]
            if any(r is not None and r.prefilled for r in self._slots):
                if not (self._speculative and self._run_spec_chunk()):
                    self._run_decode_chunk()
                self._current_batch = [r for r in self._slots
                                       if r is not None]
            self._gauge_kv_occupancy()

    def _refill_locked(self) -> List[_GenRequest]:
        """Assign queued requests to free slots (FIFO). Runs under
        ``_lock``; the assigned requests count as dispatched from here on
        (the accounting's in-flight arm)."""
        free = [j for j, r in enumerate(self._slots) if r is None]
        taken: List[_GenRequest] = []
        while free and self._queue:
            r = self._queue.pop(0)
            r.slot = free.pop(0)
            self._slots[r.slot] = r
            self._dispatched += 1
            taken.append(r)
        if taken:
            self._gauge_depth_locked()
        return taken

    # -- admission: prefix reuse + chunked prefill -----------------------
    def _admit_newcomers(self,
                         newcomers: List[_GenRequest]) -> List[_GenRequest]:
        """Route just-seated requests: a prefix-cache hit copies the
        matched pages into the slot and prefills ONLY the suffix via
        chunk slices; an over-bucket prompt goes chunked from row 0;
        everything else takes the classic bucket prefill (returned)."""
        bucketed: List[_GenRequest] = []
        for r in newcomers:
            rows = 0
            if self._prefix_cache is not None:
                rows, entries = self._prefix_cache.match(r.prompt)
                if _monitor.enabled():
                    (_monitor.counter("serving_prefix_hits_total",
                                      "requests that reused >= 1 cached "
                                      "prefix page") if rows else
                     _monitor.counter("serving_prefix_misses_total",
                                      "requests with no cached prefix "
                                      "page")).inc()
                    if rows:
                        _monitor.counter(
                            "serving_prefix_pages_reused_total",
                            "KV pages served from the prefix cache"
                        ).inc(rows // self._page_size)
                if rows:
                    self._copy_in_prefix(r.slot, entries)
                    r.prefix_rows, r.next_off, r.chunked = rows, rows, True
                    continue
            if r.chunked:
                r.next_off = 0
            else:
                bucketed.append(r)
        return bucketed

    def _copy_in_prefix(self, slot: int, entries: List[dict]) -> None:
        """Copy matched prefix pages into ``slot``'s KV rows. Copy-in (not
        aliasing) is the CoW story: the resident owns its rows outright,
        so later divergence or store eviction can never corrupt it."""
        P = self._page_size
        for li, (nk, nv) in enumerate(self._cache_names):
            for name, kv in ((nk, "k"), (nv, "v")):
                arr = np.array(self._scope.find_var(name))
                for i, e in enumerate(entries):
                    arr[slot, :, i * P:(i + 1) * P, :] = e[kv][li]
                self._scope.set_var(name, arr)

    def _publish_pages(self, r: _GenRequest) -> None:
        """After ``r``'s prefill completes, publish COPIES of its whole-
        page prompt rows under their chain hashes (cheap no-op for pages
        already stored)."""
        if self._prefix_cache is None:
            return
        P, slot = self._page_size, r.slot

        def page_rows(i):
            ks, vs = [], []
            for nk, nv in self._cache_names:
                ks.append(np.array(np.asarray(
                    self._scope.find_var(nk))[slot, :, i * P:(i + 1) * P, :]))
                vs.append(np.array(np.asarray(
                    self._scope.find_var(nv))[slot, :, i * P:(i + 1) * P, :]))
            return ks, vs

        self._prefix_cache.insert(r.prompt, page_rows)
        if _monitor.enabled():
            _monitor.gauge(
                "serving_prefix_pages",
                "KV pages resident in the prefix cache").set(
                float(len(self._prefix_cache)))

    def _deactivate_slot(self, slot: int) -> None:
        """Host-side decode-gate clear on retire: the slot's ``active``
        flag goes 0 so later decode/verify dispatches leave its state and
        cache rows untouched until the next admission re-arms it."""
        cur = self._scope.find_var("gpt_gen_active")
        if cur is None:
            return
        arr = np.array(cur)
        arr[slot, 0] = 0.0
        self._scope.set_var("gpt_gen_active", arr)

    # -- chunked prefill -------------------------------------------------
    def _chunk_feed(self, pending: Sequence[_GenRequest]) -> dict:
        B, C = len(self._slots), self._prefill_chunk
        feed = {
            "chunk_ids": np.zeros((B, C), np.int64),
            "chunk_pos": np.zeros((B, C), np.int64),
            "chunk_start": np.zeros((B, 1), np.int64),
            "chunk_len": np.ones((B, 1), np.int64),
            "slot_mask": np.zeros((B, 1), np.float32),
            "sample_mask": np.zeros((B, 1), np.float32),
        }
        for r in pending:
            off, L = r.next_off, len(r.prompt)
            take = r.prompt[off:off + C]
            n = len(take)
            feed["chunk_ids"][r.slot, :n] = take
            if n < C:
                feed["chunk_ids"][r.slot, n:] = take[-1]
            feed["chunk_pos"][r.slot] = np.clip(
                off + np.arange(C), 0, self._max_seq - 1)
            feed["chunk_start"][r.slot, 0] = off
            feed["chunk_len"][r.slot, 0] = n
            feed["slot_mask"][r.slot, 0] = 1.0
            if off + n >= L:
                feed["sample_mask"][r.slot, 0] = 1.0
        return feed

    def _run_chunk_slices(self) -> None:
        """One prefill slice for EVERY pending chunked request, batched
        into a single slot-masked dispatch. A prompt's final slice samples
        its first token in-program and flips the slot's decode gate."""
        pending = [r for r in self._slots
                   if r is not None and r.chunked and not r.prefilled]
        live: List[_GenRequest] = []
        for r in pending:
            if r.deadline is not None and r.deadline.expired:
                self._retire(r)
                self._settle_error(
                    r, "deadline_exceeded",
                    DeadlineExceeded(r.deadline.what, r.deadline.budget_s,
                                     r.deadline.elapsed()),
                    dispatched=True)
            else:
                live.append(r)
        if not live:
            return
        net = self._chunk
        span = _trace.NOOP_SPAN
        if _trace.enabled():
            span = _trace.root_span(
                "serving.prefill_chunk", requests=len(live),
                request_traces=",".join(r.span.trace_id for r in live))
        try:
            _faults.fault_point("batch_dispatch")
            feed = self._chunk_feed(live)
            t0 = time.perf_counter()
            with _trace.attach(span):
                outs = self._exe.run(net["main"], feed=feed,
                                     fetch_list=[net["first_token"].name],
                                     scope=self._scope)
            dt = time.perf_counter() - t0
        except _faults.InjectedFault as e:
            span.end(error=e)
            self._fail_group(live, e, phase="prefill_chunk")
            return
        except Exception as e:
            span.end(error=e)
            self._fail_all_resident(e, phase="prefill_chunk")
            return
        span.end()
        self._note_compiles("chunk", self._prefill_chunk, net["main"])
        self.prefill_chunks += len(live)
        if _monitor.enabled():
            _monitor.counter(
                "serving_prefill_chunks_total",
                "chunked-prefill slices dispatched (per request)"
            ).inc(len(live))
            _monitor.histogram(
                "serving_prefill_seconds",
                "wall time of one slot-masked prefill dispatch").observe(dt)
        first = np.asarray(outs[0]).reshape(len(self._slots))
        C = self._prefill_chunk
        for r in live:
            n = min(C, len(r.prompt) - r.next_off)
            r.next_off += n
            if r.next_off < len(r.prompt):
                continue
            r.prefilled = True
            self._publish_pages(r)
            if _monitor.enabled():
                _monitor.histogram(
                    "serving_first_token_seconds",
                    "submit-to-first-token latency (prefill + queue)"
                ).observe(self._now() - r.submitted)
            self._emit(r, [int(first[r.slot])], dt,
                       record_intertoken=False)

    # -- speculative decoding --------------------------------------------
    def _ngram_draft(self, hist: np.ndarray, n: int) -> List[int]:
        """Prompt-lookup drafting (model-free): find the most recent
        earlier occurrence of the last token and propose the tokens that
        followed it; pad by repeating. A wrong draft costs only its
        rejected verify rows — correctness rides on the verify dispatch,
        never the proposer."""
        last = int(hist[-1])
        prev = np.nonzero(hist[:-1] == last)[0]
        cand = hist[int(prev[-1]) + 1:int(prev[-1]) + 1 + n] \
            if prev.size else hist[:0]
        toks = [int(t) for t in cand]
        while len(toks) < n:
            toks.append(toks[-1] if toks else last)
        return toks

    def _draft(self, r: _GenRequest, n: int) -> List[int]:
        hist = np.concatenate(
            [r.prompt, np.asarray(r.out_tokens, np.int64)]) \
            if r.out_tokens else r.prompt
        if self.draft_fn is not None:
            toks = [int(t) for t in self.draft_fn(hist, n)]
            if len(toks) != n:
                raise ValueError(
                    f"serving: draft_fn returned {len(toks)} tokens, "
                    f"expected {n}")
            return toks
        return self._ngram_draft(hist, n)

    def _verify_feed(self, active: Sequence[_GenRequest]) -> dict:
        B, k = len(self._slots), self._spec_k
        feed = {
            "chunk_ids": np.zeros((B, k), np.int64),
            "chunk_pos": np.zeros((B, k), np.int64),
            "chunk_start": np.zeros((B, 1), np.int64),
            "slot_mask": np.zeros((B, 1), np.float32),
            "draft_ids": np.zeros((B, k - 1), np.int64),
        }
        for r in active:
            pos = len(r.prompt) + r.emitted - 1   # committed cache rows
            drafts = self._draft(r, k - 1)
            feed["chunk_ids"][r.slot, 0] = r.out_tokens[-1]
            feed["chunk_ids"][r.slot, 1:] = drafts
            feed["chunk_pos"][r.slot] = np.clip(
                pos + np.arange(k), 0, self._max_seq - 1)
            feed["chunk_start"][r.slot, 0] = pos
            feed["slot_mask"][r.slot, 0] = 1.0
            feed["draft_ids"][r.slot] = drafts
        return feed

    def _run_spec_chunk(self) -> bool:
        """One draft-then-verify round for every decode-eligible resident:
        the target scores the whole k-token chunk in ONE dispatch and
        commits the longest agreeing prefix + bonus token in-program.
        Returns False (caller falls back to the plain decode chunk) when
        any resident is too near its KV capacity for a full chunk."""
        active = [r for r in self._slots if r is not None and r.prefilled]
        k = self._spec_k
        if not active:
            return False
        for r in active:
            if len(r.prompt) + r.emitted - 1 + k > self._max_seq:
                return False
        span = _trace.NOOP_SPAN
        if _trace.enabled():
            span = _trace.root_span(
                "serving.spec_verify", k=k, requests=len(active),
                request_traces=",".join(r.span.trace_id for r in active))
        net = self._verify
        try:
            _faults.fault_point("batch_dispatch")
            feed = self._verify_feed(active)
            t0 = time.perf_counter()
            with _trace.attach(span):
                outs = self._exe.run(
                    net["main"], feed=feed,
                    fetch_list=[net["accept_len"].name,
                                net["sampled"].name],
                    scope=self._scope)
            dt = time.perf_counter() - t0
        except _faults.InjectedFault as e:
            span.end(error=e)
            self._fail_group(active, e, phase="spec_verify")
            return True
        except Exception as e:
            span.end(error=e)
            self._fail_all_resident(e, phase="spec_verify")
            return True
        span.end()
        self._note_compiles("verify", k, net["main"])
        self.spec_chunks += 1
        accept = np.asarray(outs[0]).reshape(len(self._slots))
        sampled = np.asarray(outs[1]).reshape(len(self._slots), k)
        if _monitor.enabled():
            _monitor.histogram(
                "serving_decode_chunk_seconds",
                "wall time of one chained decode chunk").observe(dt)
        for r in active:
            if r.deadline is not None and r.deadline.expired:
                self._retire(r)
                self._settle_error(
                    r, "deadline_exceeded",
                    DeadlineExceeded(r.deadline.what, r.deadline.budget_s,
                                     r.deadline.elapsed()),
                    dispatched=True)
                continue
            m = int(accept[r.slot])
            self.spec_accepted += m
            if _monitor.enabled():
                _monitor.histogram(
                    "serving_spec_accepted_len",
                    "draft tokens accepted per verify chunk (0..k-1; the "
                    "bonus token is on top)").observe(float(m))
            take = sampled[r.slot, :m + 1][:r.max_new - r.emitted]
            eos = self.gen_config.eos_id
            if eos >= 0:
                hits = np.nonzero(take == eos)[0]
                if hits.size:
                    take = take[:int(hits[0]) + 1]
            self._emit(r, [int(t) for t in take], dt)
        return True

    # -- prefill ---------------------------------------------------------
    def _prefill_feed(self, bucket: int,
                      reqs: Sequence[_GenRequest]) -> dict:
        B = len(self._slots)
        feed = {
            "prompt_ids": np.zeros((B, bucket), np.int64),
            "prompt_pos": np.tile(np.arange(bucket, dtype=np.int64),
                                  (B, 1)),
            "prompt_mask": np.zeros((B, bucket), np.float32),
            "prompt_len": np.ones((B, 1), np.int64),
            "slot_mask": np.zeros((B, 1), np.float32),
        }
        for r in reqs:
            L = len(r.prompt)
            feed["prompt_ids"][r.slot, :L] = r.prompt
            feed["prompt_mask"][r.slot, :L] = 1.0
            feed["prompt_len"][r.slot, 0] = L
            feed["slot_mask"][r.slot, 0] = 1.0
        return feed

    def _run_prefill(self, newcomers: List[_GenRequest]) -> None:
        by_bucket = defaultdict(list)
        for r in newcomers:
            by_bucket[r.bucket].append(r)
        for bucket in sorted(by_bucket):
            reqs = by_bucket[bucket]
            net = self._model["prefill"][bucket]
            span = _trace.NOOP_SPAN
            if _trace.enabled():
                span = _trace.root_span(
                    "serving.prefill", bucket=bucket, requests=len(reqs),
                    request_traces=",".join(r.span.trace_id for r in reqs))
                for r in reqs:
                    r.dispatch_span = _trace.start_span(
                        "serving.dispatch", parent=r.span, phase="prefill",
                        bucket=bucket, slot=r.slot)
            try:
                _faults.fault_point("batch_dispatch")
                feed = self._prefill_feed(bucket, reqs)
                t0 = time.perf_counter()
                with _trace.attach(span):
                    outs = self._exe.run(net["main"], feed=feed,
                                         fetch_list=[net["first_token"].name],
                                         scope=self._scope)
                dt = time.perf_counter() - t0
            except _faults.InjectedFault as e:
                # fired before any dispatch: state intact, only this
                # group fails (typed) — the engine keeps serving
                span.end(error=e)
                self._fail_group(reqs, e, phase="prefill")
                continue
            except Exception as e:
                # a real failure may have consumed donated state buffers:
                # fail every resident stream typed + reset the state
                span.end(error=e)
                self._fail_all_resident(e, phase="prefill")
                return
            span.end()
            self._note_compiles("prefill", bucket, net["main"])
            if _monitor.enabled():
                _monitor.histogram(
                    "serving_prefill_seconds",
                    "wall time of one slot-masked prefill dispatch"
                ).observe(dt)
            first = np.asarray(outs[0]).reshape(len(self._slots))
            for r in reqs:
                r.prefilled = True
                r.next_off = len(r.prompt)
                self._publish_pages(r)
                if r.deadline is not None and r.deadline.expired:
                    self._retire(r)
                    self._settle_error(
                        r, "deadline_exceeded",
                        DeadlineExceeded(r.deadline.what,
                                         r.deadline.budget_s,
                                         r.deadline.elapsed()),
                        dispatched=True)
                    continue
                if _monitor.enabled():
                    _monitor.histogram(
                        "serving_first_token_seconds",
                        "submit-to-first-token latency (prefill + queue)"
                    ).observe(self._now() - r.submitted)
                # the first token's cost is the FIRST-TOKEN histogram's
                # story — it must not pollute the inter-token latency
                self._emit(r, [int(first[r.slot])], dt,
                           record_intertoken=False)

    # -- decode ----------------------------------------------------------
    def _run_decode_chunk(self) -> None:
        # only decode-eligible residents: slots mid-chunked-prefill keep
        # their in-program decode gate (``gpt_gen_active``) at 0, so the
        # dispatch leaves their state and cache rows bit-untouched
        active = [r for r in self._slots if r is not None and r.prefilled]
        steps = self.gen_config.decode_chunk
        span = _trace.NOOP_SPAN
        if _trace.enabled():
            span = _trace.root_span(
                "serving.decode", steps=steps, requests=len(active),
                request_traces=",".join(r.span.trace_id for r in active))
        try:
            _faults.fault_point("batch_dispatch")
            t0 = time.perf_counter()
            with _trace.attach(span):
                outs = self._exe.run_chained(
                    self._program, feed={}, fetch_list=self._fetch_names,
                    steps=steps, scope=self._scope)
            dt = time.perf_counter() - t0
        except _faults.InjectedFault as e:
            # the chaos gate's kill-one-batch: every stream in THIS batch
            # settles typed; state untouched (the fault fires before the
            # dispatch), freed slots are re-prefilled next iteration
            span.end(error=e)
            self._fail_group(active, e, phase="decode")
            return
        except Exception as e:
            span.end(error=e)
            self._fail_all_resident(e, phase="decode")
            return
        span.end()
        self._note_compiles("decode", len(self._slots), self._program)
        toks = np.asarray(outs[0]).reshape(steps, len(self._slots))
        per_tok = dt / steps
        if _monitor.enabled():
            _monitor.histogram(
                "serving_decode_chunk_seconds",
                "wall time of one chained decode chunk").observe(dt)
        for r in active:
            if r.deadline is not None and r.deadline.expired:
                # mid-stream expiry: the typed outcome is the LAST word —
                # this chunk's tokens are discarded, the ones already
                # streamed remain readable as partial results
                self._retire(r)
                self._settle_error(
                    r, "deadline_exceeded",
                    DeadlineExceeded(r.deadline.what, r.deadline.budget_s,
                                     r.deadline.elapsed()),
                    dispatched=True)
                continue
            take = toks[:r.max_new - r.emitted, r.slot]
            eos = self.gen_config.eos_id
            if eos >= 0:
                hits = np.nonzero(take == eos)[0]
                if hits.size:
                    take = take[:int(hits[0]) + 1]
            self._emit(r, [int(t) for t in take], per_tok * len(take))

    # -- shared settle paths ---------------------------------------------
    def _emit(self, r: _GenRequest, toks: List[int], dt: float,
              record_intertoken: bool = True) -> None:
        """Stream ``toks`` to the future (partial results) and settle the
        request when it reaches its token budget or stop token.
        ``record_intertoken=False`` on the prefill-produced first token:
        its cost belongs to ``serving_first_token_seconds``, not the
        inter-token distribution."""
        if toks:
            r.future._emit_tokens(toks)
            r.out_tokens.extend(toks)
            r.emitted += len(toks)
            if _monitor.enabled():
                _monitor.counter(
                    "serving_decode_tokens_total",
                    "tokens streamed to generative requests").inc(len(toks))
                if record_intertoken:
                    h = _monitor.histogram(
                        "serving_intertoken_seconds",
                        "per-token wall time within a decode chunk "
                        "(p50/p99 in the snapshot)")
                    for _ in toks:
                        h.observe(dt / len(toks))
        done = r.emitted >= r.max_new
        eos = self.gen_config.eos_id
        if not done and eos >= 0 and toks and toks[-1] == eos:
            done = True
        if done:
            self._retire(r)
            latency = self._now() - r.submitted
            with self._lock:
                self._acct["completed"] += 1
                self._dispatched -= 1
            self._record_outcome("completed")
            self._finish_request(r, "completed")
            if _monitor.enabled():
                # same exemplar contract as the base engine's _distribute
                ex = r.span.trace_id \
                    if _monitor.telemetry_enabled() else None
                _monitor.histogram(
                    "serving_request_latency_seconds",
                    "submit-to-response latency of completed requests "
                    "(p50/p99 in the snapshot)").observe(
                    latency, exemplar=ex or None)
            r.future._settle(
                result=[np.asarray(r.out_tokens, dtype=np.int64)])

    def _retire(self, r: _GenRequest) -> None:
        if 0 <= r.slot < len(self._slots) and self._slots[r.slot] is r:
            self._slots[r.slot] = None
            self._deactivate_slot(r.slot)

    def _fail_group(self, reqs: List[_GenRequest], err: BaseException,
                    phase: str) -> None:
        logger.warning(
            "serving: %s dispatch of %d stream(s) failed (%s: %s) — "
            "failing those streams typed, engine continues",
            phase, len(reqs), type(err).__name__, err)
        if _monitor.enabled():
            _monitor.counter("serving_batches_total",
                             "dispatched batches by result").labels(
                result="failed").inc()
        for r in reqs:
            self._retire(r)
            e = BatchFailed(
                f"serving: {phase} batch failed for stream #{r.seq}: "
                f"{type(err).__name__}: {err}")
            e.__cause__ = err
            self._settle_error(r, "failed", e, dispatched=True)
        _trace.record_incident(
            "batch_failed", error=err,
            context=reqs[0].span if reqs else None,
            detail=f"generative {phase}, {len(reqs)} stream(s)")

    def _fail_all_resident(self, err: BaseException, phase: str) -> None:
        resident = [r for r in self._slots if r is not None]
        logger.error(
            "serving: %s dispatch raised %s — generation state may hold "
            "consumed buffers; failing all %d resident stream(s) typed "
            "and resetting the generation state",
            phase, type(err).__name__, len(resident))
        self._fail_group(resident, err, phase)
        self.reset_generation_state()

    # -- observability ---------------------------------------------------
    def _program_steps(self, program) -> frozenset:
        """Identities of the executor-cached compiled steps belonging to
        ``program`` — run-path keys lead with the program fingerprint
        ``(serial, ...)``, chained keys with ``("chained", fingerprint,
        ...)``. Scoped per program so unrelated compiles on a SHARED
        executor (a trainer thread, a sibling engine) can never read as
        this engine's recompiles."""
        serial = getattr(program, "_serial", None)
        with self._exe._lock:
            return frozenset(
                id(step) for key, step in self._exe._cache.items()
                if (key[0] == "chained" and key[1][0] == serial)
                or (isinstance(key[0], tuple) and key[0]
                    and key[0][0] == serial))

    def _note_compiles(self, phase: str, bucket: int, program) -> None:
        """The bucketed-recompile watchdog: a (phase, bucket) whose
        executable already exists must NEVER compile again — positions
        move, shapes don't. A NEW compiled step appearing for this
        phase's program after its first compile is counted on
        ``serving_decode_recompiles_total`` and logged loudly; the
        ``load_check --decode`` gate fails on a non-zero total."""
        key = (phase, int(bucket))
        steps = self._program_steps(program)
        prev = self._compiled_buckets.get(key)
        if prev is None:
            self._compiled_buckets[key] = steps
            return
        if steps - prev:
            self.decode_recompiles += 1
            logger.error(
                "serving: RECOMPILE on warm (phase=%s, bucket=%s) — a new "
                "executable was compiled for a program that was already "
                "compiled; KV growth must never reshape a decode dispatch",
                phase, bucket)
            if _monitor.enabled():
                _monitor.counter(
                    "serving_decode_recompiles_total",
                    "executable compiles beyond one per (phase, bucket) — "
                    "always a bug; gated to zero in CI").labels(
                    phase=phase, bucket=str(bucket)).inc()
            self._compiled_buckets[key] = prev | steps

    def _gauge_kv_occupancy(self) -> None:
        if not _monitor.enabled():
            return
        pages = self._max_seq // self._page_size
        used = 0
        for r in self._slots:
            if r is not None:
                length = min(len(r.prompt) + r.emitted, self._max_seq)
                used += -(-length // self._page_size)   # ceil
        _monitor.gauge(
            "serving_kv_page_occupancy",
            "fraction of KV cache pages held by resident sequences"
        ).set(used / (pages * len(self._slots)))

    def generation_stats(self) -> dict:
        """Decode-side snapshot for reports: resident slots, compiled
        (phase, bucket) executables, recompiles, prefix-cache and
        speculative-decoding counters."""
        resident = [r.seq for r in self._slots if r is not None]
        pc = self._prefix_cache
        return {
            "slots": len(self._slots),
            "resident": resident,
            "compiled_buckets": sorted(
                f"{p}:{b}" for (p, b) in self._compiled_buckets),
            "decode_recompiles": self.decode_recompiles,
            "max_seq": self._max_seq,
            "page_size": self._page_size,
            "prompt_buckets": list(self._buckets),
            "prefill_chunk": self._prefill_chunk,
            "prefill_chunks": self.prefill_chunks,
            "prefix_cache": pc.stats() if pc is not None else None,
            "speculative": {
                "enabled": self._speculative,
                "k": self._spec_k if self._speculative else 0,
                "chunks": self.spec_chunks,
                "accepted_tokens": self.spec_accepted,
            },
        }
