"""Multi-window SLO burn-rate tracking per priority class.

The sensor half of the fleet control loop (ROADMAP item 5): every
terminal request outcome is scored against a declared per-class latency
objective — a completed request slower than its class target, or ANY
non-completed terminal outcome, consumes error budget — and the burn
rate (observed bad fraction / allowed bad fraction) is tracked over two
windows, the classic fast+slow multi-window burn alert:

* **fast** (default 60 s): pages quickly when the budget is burning hard;
* **slow** (default 600 s): confirms the burn is sustained, so a single
  bad second never flips the state alone.

``state()`` reduces to ``ok`` (neither window burning), ``warning``
(exactly one window >= 1x budget) or ``burning`` (both) — the payload
rides ``ServingEngine.health()`` under the additive ``"slo"`` key, so
the PR 15 supervisor and the future autoscaler read it over the wire
for free. Objectives come from ``ServingConfig``
(``FLAGS_serving_slo_*`` defaults); docs/SERVING.md "SLO burn rate".

Layering note: this module sits BELOW the fleet tier on purpose —
``serving.engine`` owns a tracker, and ``serving.fleet`` only ever sees
the serialized state dict.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Dict, Optional

__all__ = ["SloBurnTracker", "parse_latency_targets",
           "class_for_priority", "PRIORITY_CLASS_NAMES",
           "STATE_ORDER"]

# priority -> class name, matching fleet.wire.SLO_CLASSES by construction
# (batch=0 / standard=1 / interactive=2); priorities outside the declared
# classes clamp to the nearest one so an explicit priority=7 request is
# still tracked (as the strictest class) instead of invisible.
PRIORITY_CLASS_NAMES = ("batch", "standard", "interactive")
STATE_ORDER = ("ok", "warning", "burning")

_DEFAULT_TARGETS = "batch:30,standard:1.0,interactive:0.25"


def class_for_priority(priority: int) -> str:
    p = max(0, min(int(priority), len(PRIORITY_CLASS_NAMES) - 1))
    return PRIORITY_CLASS_NAMES[p]


def parse_latency_targets(spec: Optional[str]) -> Dict[str, float]:
    """Parse ``'class:seconds,...'`` into ``{class: target_s}``; unknown
    class names raise (a typo would silently stop tracking that class)."""
    spec = (spec or "").strip() or _DEFAULT_TARGETS
    out: Dict[str, float] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" not in part:
            raise ValueError(
                f"bad SLO latency spec {part!r} (want 'class:seconds')")
        name, _, val = part.partition(":")
        name = name.strip()
        if name not in PRIORITY_CLASS_NAMES:
            raise ValueError(
                f"unknown SLO class {name!r} — "
                f"known: {PRIORITY_CLASS_NAMES}")
        out[name] = float(val)
        if out[name] <= 0:
            raise ValueError(f"SLO latency target must be > 0: {part!r}")
    return out


class _ClassWindow:
    """Per-class good/bad counts in 1-second buckets, bounded to the
    slow window."""

    __slots__ = ("buckets", "target_s")

    def __init__(self, target_s: float):
        self.target_s = target_s
        # deque of [second:int, good:int, bad:int], oldest first
        self.buckets = collections.deque()

    def observe(self, now_s: int, bad: bool, keep_s: float) -> None:
        if self.buckets and self.buckets[-1][0] == now_s:
            slot = self.buckets[-1]
        else:
            slot = [now_s, 0, 0]
            self.buckets.append(slot)
        slot[2 if bad else 1] += 1
        horizon = now_s - keep_s
        while self.buckets and self.buckets[0][0] < horizon:
            self.buckets.popleft()

    def totals(self, now_s: int, window_s: float):
        good = bad = 0
        horizon = now_s - window_s
        for sec, g, b in self.buckets:
            if sec > horizon:
                good += g
                bad += b
        return good, bad


class SloBurnTracker:
    """Thread-safe burn-rate tracker; one per engine.

    ``observe()`` is called from the engine's settle paths (terminal
    outcome known) — it is a few dict/int ops under the tracker's own
    lock, safe under the engine lock. ``state()`` serializes the whole
    tracker for the health payload and refreshes the ``slo_burn_*``
    registry gauges.
    """

    def __init__(self, targets: Dict[str, float],
                 error_budget: float = 0.01,
                 fast_window_s: float = 60.0,
                 slow_window_s: float = 600.0,
                 _now=time.monotonic):
        if error_budget <= 0 or error_budget > 1:
            raise ValueError(
                f"error budget must be in (0, 1]: {error_budget}")
        if fast_window_s <= 0 or slow_window_s < fast_window_s:
            raise ValueError(
                "windows must satisfy 0 < fast <= slow: "
                f"{fast_window_s} / {slow_window_s}")
        self.error_budget = float(error_budget)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self._now = _now
        from paddle_tpu import monitor

        self._lock = monitor.make_lock("SloBurnTracker._lock")
        self._classes = {name: _ClassWindow(t)
                         for name, t in sorted(targets.items())}

    def observe(self, priority: int, latency_s: Optional[float],
                error: bool) -> None:
        """Record one terminal outcome. ``error`` marks any
        non-completed outcome; a completed request is bad iff slower
        than its class latency target. Unknown classes (no declared
        objective) are not tracked."""
        cls = self._classes.get(class_for_priority(priority))
        if cls is None:
            return
        bad = bool(error) or (latency_s is not None
                              and latency_s > cls.target_s)
        now_s = int(self._now())
        with self._lock:
            cls.observe(now_s, bad, self.slow_window_s)

    def _burn(self, cls: _ClassWindow, now_s: int,
              window_s: float) -> Optional[float]:
        good, bad = cls.totals(now_s, window_s)
        total = good + bad
        if not total:
            return None
        return (bad / total) / self.error_budget

    def state(self) -> dict:
        """Serializable tracker state (the health payload's ``"slo"``
        value): per-class fast/slow burn rates and reduced states, plus
        the worst class state at the top. Refreshes the registry's
        ``slo_burn_rate{class,window}`` / ``slo_burn_state{class}``
        gauges as a side effect (the scrapeable mirror)."""
        from paddle_tpu import monitor

        now_s = int(self._now())
        classes = {}
        worst = "ok"
        with self._lock:
            for name, cls in self._classes.items():
                fast = self._burn(cls, now_s, self.fast_window_s)
                slow = self._burn(cls, now_s, self.slow_window_s)
                hot = sum(1 for b in (fast, slow)
                          if b is not None and b >= 1.0)
                st = STATE_ORDER[hot]
                if STATE_ORDER.index(st) > STATE_ORDER.index(worst):
                    worst = st
                good, bad = cls.totals(now_s, self.slow_window_s)
                classes[name] = {
                    "target_s": cls.target_s,
                    "fast_burn": fast,
                    "slow_burn": slow,
                    "state": st,
                    "good": good,
                    "bad": bad,
                }
        if monitor.enabled():
            for name, c in classes.items():
                monitor.gauge(
                    "slo_burn_rate",
                    "SLO burn rate (bad fraction / error budget) per "
                    "priority class and window").labels(
                        **{"class": name, "window": "fast"}).set(
                            c["fast_burn"] or 0.0)
                monitor.gauge("slo_burn_rate").labels(
                    **{"class": name, "window": "slow"}).set(
                        c["slow_burn"] or 0.0)
                monitor.gauge(
                    "slo_burn_state",
                    "reduced SLO state per class: 0=ok 1=warning "
                    "2=burning").labels(**{"class": name}).set(
                        STATE_ORDER.index(c["state"]))
        return {
            "state": worst,
            "error_budget": self.error_budget,
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "classes": classes,
        }
