"""Per-bucket circuit breaker for the serving engine.

One breaker guards one (feed signature, padded batch) shape bucket — the
unit that maps 1:1 onto a compiled executable in the executor's step
cache. A bucket whose compiles keep failing (the compile site already
retries transients with backoff — ``resilience.retry`` inside
``Executor._ensure_executable``; what reaches the breaker has outlasted
that budget) must stop eating every request routed to it: the breaker
OPENs after ``FLAGS_serving_breaker_threshold`` consecutive batch
failures and the engine rejects that bucket's requests with typed
:class:`~paddle_tpu.serving.CircuitOpen` instead of queueing them into a
known-broken executable.

The open->half-open cooldown reuses the retry subsystem's backoff
schedule (:class:`resilience.retry.RetryPolicy` — doubling, capped,
seeded jitter) keyed by how many times this bucket has re-opened: a
bucket that keeps failing its probe batches backs off exactly like a
transient site that keeps failing its retries, one implementation for
both. A successful probe CLOSEs the breaker and resets the schedule.

Thread model: ``allow``/``record_*`` are only called from the engine's
single dispatch thread; ``state``/``snapshot`` may be read from any
thread (health probes) and only read immutable-enough scalars.
"""
from __future__ import annotations

import random
import time
import zlib
from typing import Optional

from ..resilience.retry import RetryPolicy

__all__ = ["CircuitBreaker", "CLOSED", "OPEN", "HALF_OPEN"]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    def __init__(self, threshold: int, cooldown_s: float,
                 name: str = "", seed: int = 0,
                 emit_transitions: bool = True):
        """``emit_transitions=False`` suppresses the
        ``serving_breaker_transitions_total`` emission — for reusers of
        the state machine that own their OWN transition metric (the
        fleet router's per-replica transport breakers emit
        ``router_breaker_transitions_total`` instead; the serving metric
        must keep meaning 'bucket breakers' as documented)."""
        self.name = name
        self.emit_transitions = emit_transitions
        self.threshold = max(1, int(threshold))
        # the cooldown ladder IS a retry backoff: attempt k of the policy
        # = the k-th consecutive re-open of this bucket
        self._policy = RetryPolicy(max_attempts=1_000_000,
                                   base_delay=float(cooldown_s),
                                   max_delay=max(float(cooldown_s) * 16, 1e-3),
                                   multiplier=2.0, jitter=0.25,
                                   timeout=None)
        self._rng = random.Random((int(seed) << 16)
                                  ^ zlib.crc32(name.encode() or b"bucket"))
        self._state = CLOSED
        self._consecutive_failures = 0
        self._open_streak = 0          # consecutive opens without a close
        self._opened_at: Optional[float] = None
        self._cooldown: float = 0.0
        self.transitions = 0

    @property
    def state(self) -> str:
        return self._state

    def allow(self, now: Optional[float] = None) -> str:
        """Admission verdict for one batch: ``"yes"`` (closed),
        ``"probe"`` (open long enough — let exactly one batch test the
        bucket, moving to half-open) or ``"no"`` (still cooling down)."""
        if self._state == CLOSED:
            return "yes"
        now = time.monotonic() if now is None else now
        if self._state == OPEN and now - self._opened_at >= self._cooldown:
            self._transition(HALF_OPEN)
            return "probe"
        # HALF_OPEN between allow() and its record_* resolution never
        # admits a second batch; the dispatcher is single-threaded so
        # this is only reachable if a caller skipped record_*
        return "no"

    def record_success(self) -> None:
        self._consecutive_failures = 0
        self._open_streak = 0
        if self._state != CLOSED:
            self._transition(CLOSED)

    def record_failure(self, now: Optional[float] = None) -> None:
        self._consecutive_failures += 1
        tripped = (self._state == HALF_OPEN        # failed probe: re-open
                   or self._consecutive_failures >= self.threshold)
        if tripped and self._state != OPEN:
            self._open_streak += 1
            self._opened_at = time.monotonic() if now is None else now
            self._cooldown = self._policy.delay(self._open_streak, self._rng)
            self._transition(OPEN)

    def _transition(self, to: str) -> None:
        from .. import monitor as _monitor

        self._state = to
        self.transitions += 1
        if self.emit_transitions and _monitor.enabled():
            _monitor.counter(
                "serving_breaker_transitions_total",
                "circuit-breaker state changes by target state").labels(
                to=to).inc()

    def snapshot(self) -> dict:
        return {"name": self.name, "state": self._state,
                "consecutive_failures": self._consecutive_failures,
                "open_streak": self._open_streak,
                "cooldown_s": round(self._cooldown, 4),
                "transitions": self.transitions}
