"""Radix/prefix KV cache: content-addressed prompt pages shared across
requests (ISSUE 20 tentpole, leg a).

LLM traffic is prefix-heavy — shared system prompts, few-shot templates,
multi-turn resubmissions all repeat the same leading tokens. The paged KV
layout already stores a prompt as whole ``page_size``-row blocks, so the
reusable unit is a PAGE and the identity of a page is the token content
of that page *and every page before it* (attention rows depend on the
whole preceding context). :class:`PrefixCache` therefore keys entries by
a **chain hash**::

    h_0 = sha256(page_0 token bytes)
    h_i = sha256(h_{i-1} || page_i token bytes)

so two prompts share cached pages exactly as far as their token streams
agree on whole-page boundaries — a radix-tree lookup flattened into one
hash map (the chain hash IS the path key).

Sharing is **copy-on-write by copy-in**: a hit copies the cached K/V rows
into the requester's slot pages, and a completed prefill publishes copies
of its freshly computed pages. Residents never alias the store, so

* divergence after a shared prefix (the mid-page CoW case) only ever
  mutates the resident's own slot pages, and
* eviction can never corrupt a resident mid-decode — the entry being
  dropped was a source of copies, not a shared mapping.

That trades copy bandwidth for an aliasing-proof invariant, the right
trade at host-side page sizes (a page is ``page_size * hidden`` floats
per layer). The store is bounded (``capacity_pages``) with LRU eviction
— the PR 15 quarantine idiom: an ``OrderedDict`` whose hits
``move_to_end`` and whose inserts pop the stalest entries past capacity.

The LAST prompt token is never cached: its logits produce the request's
first generated token, so the suffix after the matched pages is always
non-empty and every request still runs at least one (chunked) prefill
slice. Thread-safety is the engine's dispatcher-thread discipline — the
cache is only touched from the scheduling loop, like the slot table.
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["PrefixCache"]


class PrefixCache:
    """Bounded chain-hash store of prompt KV pages.

    An entry holds ONE page of K/V rows per transformer layer (each
    ``[num_heads, page_size, head_dim]``), keyed by the chain hash of the
    prompt up to and including that page. ``capacity_pages`` bounds the
    total page count; inserts evict least-recently-used entries past it.
    """

    def __init__(self, page_size: int, capacity_pages: int = 64):
        if page_size < 1:
            raise ValueError(f"prefix cache: page_size must be >= 1, got "
                             f"{page_size}")
        if capacity_pages < 1:
            raise ValueError(f"prefix cache: capacity_pages must be >= 1, "
                             f"got {capacity_pages}")
        self.page_size = int(page_size)
        self.capacity_pages = int(capacity_pages)
        self._entries: "OrderedDict[bytes, dict]" = OrderedDict()
        # counters (read by the engine's stats/metrics)
        self.hits = 0            # requests that matched >= 1 page
        self.misses = 0          # requests that matched 0 pages
        self.pages_reused = 0    # total pages served from the store
        self.pages_inserted = 0
        self.evictions = 0

    # -- hashing ---------------------------------------------------------
    def _chain(self, prompt: np.ndarray) -> List[bytes]:
        """Chain hashes of every whole page of ``prompt[:-1]`` (the last
        token is never cached — it must produce the first logits)."""
        P = self.page_size
        n = (int(prompt.shape[0]) - 1) // P
        hashes, h = [], b""
        for i in range(n):
            page = np.ascontiguousarray(
                prompt[i * P:(i + 1) * P].astype(np.int64))
            h = hashlib.sha256(h + page.tobytes()).digest()
            hashes.append(h)
        return hashes

    # -- lookup / publish ------------------------------------------------
    def match(self, prompt: np.ndarray) -> Tuple[int, List[dict]]:
        """Longest cached prefix of ``prompt``: returns ``(rows,
        entries)`` where ``rows = len(entries) * page_size`` and each
        entry has ``"k"``/``"v"`` per-layer page arrays to copy into the
        requester's slot. Counts one hit (>= 1 page) or one miss."""
        matched: List[dict] = []
        for h in self._chain(np.asarray(prompt)):
            e = self._entries.get(h)
            if e is None:
                break
            self._entries.move_to_end(h)
            matched.append(e)
        if matched:
            self.hits += 1
            self.pages_reused += len(matched)
        else:
            self.misses += 1
        return len(matched) * self.page_size, matched

    def insert(self, prompt: np.ndarray, page_rows) -> int:
        """Publish the whole-page prefix of a freshly prefilled prompt.
        ``page_rows(page_index) -> (k_pages, v_pages)`` returns per-layer
        COPIES of the slot's cache rows ``[page*P, (page+1)*P)`` (each
        ``[num_heads, page_size, head_dim]``); it is only called for
        pages not already stored. Returns the number of new pages."""
        added = 0
        for i, h in enumerate(self._chain(np.asarray(prompt))):
            if h in self._entries:
                self._entries.move_to_end(h)
                continue
            k_pages, v_pages = page_rows(i)
            self._entries[h] = {"k": list(k_pages), "v": list(v_pages)}
            self.pages_inserted += 1
            added += 1
        while len(self._entries) > self.capacity_pages:
            self._entries.popitem(last=False)
            self.evictions += 1
        return added

    # -- maintenance -----------------------------------------------------
    def evict_all(self) -> int:
        """Drop every entry (tests + admin reset). Safe at any time: the
        store is copy-in/copy-out, residents hold no references."""
        n = len(self._entries)
        self.evictions += n
        self._entries.clear()
        return n

    def __len__(self) -> int:
        return len(self._entries)

    def stats(self) -> Dict[str, int]:
        return {
            "pages": len(self._entries),
            "capacity_pages": self.capacity_pages,
            "hits": self.hits,
            "misses": self.misses,
            "pages_reused": self.pages_reused,
            "pages_inserted": self.pages_inserted,
            "evictions": self.evictions,
        }
