"""Inference stack: predictor API + StableHLO export.

Reference: paddle/fluid/inference/api/analysis_predictor.h:47
AnalysisPredictor (Run/ZeroCopyRun over an analysed program),
paddle_analysis_config.h AnalysisConfig, ZeroCopyTensor, and the engine
bridges (tensorrt/anakin subgraph engines).

TPU-native design: the "analysis passes + engine" pipeline is XLA — a saved
inference model is pruned, loaded, jit-compiled once per feed signature,
and cached. The TensorRT/Anakin role (portable serving artifact compiled
outside Python) is played by **StableHLO export** via ``jax.export``: the
artifact embeds the weights and runs from any PJRT runtime without
paddle_tpu installed.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import io as io_mod
from ..executor import CPUPlace, Executor, Scope, TPUPlace, scope_guard

__all__ = ["AnalysisConfig", "AnalysisPredictor", "ZeroCopyTensor",
           "create_paddle_predictor", "export_stablehlo", "load_stablehlo",
           "StableHLOPredictor"]


class AnalysisConfig:
    """reference paddle_analysis_config.h — the knobs that still mean
    something plus accepted-for-parity switches (XLA owns fusion/memory)."""

    def __init__(self, model_dir: Optional[str] = None,
                 prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        self._model_dir = model_dir
        self._prog_file = prog_file
        self._params_file = params_file
        self._use_accelerator = True
        self._memory_optim = True  # inert: XLA buffer assignment

    def set_model(self, model_dir: str):
        self._model_dir = model_dir

    def model_dir(self) -> str:
        return self._model_dir

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_accelerator = True  # the accelerator here is the TPU

    def disable_gpu(self):
        self._use_accelerator = False

    def use_gpu(self) -> bool:
        return self._use_accelerator

    def enable_memory_optim(self):
        self._memory_optim = True

    def switch_use_feed_fetch_ops(self, flag: bool):
        pass  # feed/fetch are executor-spliced, never ops

    def switch_ir_optim(self, flag: bool = True):
        pass  # XLA always optimises

    def enable_tensorrt_engine(self, **kw):
        raise NotImplementedError(
            "TensorRT has no TPU analogue — use export_stablehlo() for a "
            "portable compiled-serving artifact")


class ZeroCopyTensor:
    """reference api/paddle_api.h ZeroCopyTensor: named input/output handle
    with copy_from_cpu/copy_to_cpu."""

    def __init__(self, name: str, owner: "AnalysisPredictor", is_input: bool):
        self.name = name
        self._owner = owner
        self._is_input = is_input

    def copy_from_cpu(self, arr: np.ndarray) -> None:
        if not self._is_input:
            raise RuntimeError(f"'{self.name}' is an output tensor")
        self._owner._feeds[self.name] = np.asarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        if self._is_input:
            return np.asarray(self._owner._feeds[self.name])
        return np.asarray(self._owner._outputs[self.name])

    def shape(self):
        return list(self.copy_to_cpu().shape)


class AnalysisPredictor:
    """reference analysis_predictor.h:47. One predictor = one loaded
    inference program + its own scope + a compile cache (inside Executor)."""

    def __init__(self, config: AnalysisConfig):
        self._config = config
        place = TPUPlace() if config.use_gpu() else CPUPlace()
        self._exe = Executor(place)
        self._scope = Scope()
        model_dir = config.model_dir()
        model_fn = params_fn = None
        if model_dir is None:
            # combined-file form: AnalysisConfig(prog_file, params_file)
            if not (config._prog_file and config._params_file):
                raise ValueError(
                    "AnalysisConfig needs model_dir or both prog_file and "
                    "params_file")
            model_dir = os.path.dirname(config._prog_file) or "."
            model_fn = os.path.basename(config._prog_file)
            params_fn = os.path.basename(config._params_file)
        with scope_guard(self._scope):
            self._program, self._feed_names, fetch_vars = \
                io_mod.load_inference_model(model_dir, self._exe,
                                            model_filename=model_fn,
                                            params_filename=params_fn)
        self._fetch_names = [v.name for v in fetch_vars]
        self._feeds: Dict[str, np.ndarray] = {}
        self._outputs: Dict[str, np.ndarray] = {}

    # -- names & handles --------------------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._feed_names)

    def get_output_names(self) -> List[str]:
        return list(self._fetch_names)

    def get_input_handle(self, name: str) -> ZeroCopyTensor:
        if name not in self._feed_names:
            raise KeyError(f"unknown input '{name}'; have {self._feed_names}")
        return ZeroCopyTensor(name, self, is_input=True)

    def get_output_handle(self, name: str) -> ZeroCopyTensor:
        if name not in self._fetch_names:
            raise KeyError(f"unknown output '{name}'")
        return ZeroCopyTensor(name, self, is_input=False)

    get_input_tensor = get_input_handle
    get_output_tensor = get_output_handle

    # -- execution --------------------------------------------------------
    def run(self, inputs: Optional[Sequence[np.ndarray]] = None):
        """With ``inputs``: positional arrays aligned with input names
        (reference Run(inputs, &outputs)); without: ZeroCopyRun over the
        handles filled via copy_from_cpu."""
        if inputs is not None:
            if len(inputs) != len(self._feed_names):
                raise ValueError(
                    f"expected {len(self._feed_names)} inputs "
                    f"({self._feed_names}), got {len(inputs)}")
            self._feeds = dict(zip(self._feed_names,
                                   (np.asarray(a) for a in inputs)))
        missing = [n for n in self._feed_names if n not in self._feeds]
        if missing:
            raise RuntimeError(f"inputs not set: {missing}")
        with scope_guard(self._scope):
            outs = self._exe.run(self._program, feed=dict(self._feeds),
                                 fetch_list=self._fetch_names)
        self._outputs = dict(zip(self._fetch_names, outs))
        return [self._outputs[n] for n in self._fetch_names]

    zero_copy_run = run


def create_paddle_predictor(config: AnalysisConfig) -> AnalysisPredictor:
    """reference CreatePaddlePredictor<AnalysisConfig>."""
    return AnalysisPredictor(config)


# ---------------------------------------------------------------------------
# StableHLO export (the TRT/Anakin replacement)
# ---------------------------------------------------------------------------

def export_stablehlo(program, feed_specs: Dict[str, tuple], fetch_list,
                     path: str, scope=None):
    """Serialize an inference program as a portable StableHLO artifact.

    feed_specs: {name: (shape, dtype)} fixing the signature. Writes
    ``<path>`` (jax.export binary, runs from any PJRT runtime via
    ``load_stablehlo``) and ``<path>.mlir`` (human-readable StableHLO).
    Weights are embedded as constants — the artifact is self-contained
    (the role of a frozen TRT engine)."""
    import jax
    from jax import export as jexport

    from ..executor import analyze_block_io, global_scope, make_step_fn

    scope = scope or global_scope()
    fetch_names = [f if isinstance(f, str) else f.name for f in fetch_list]
    feed_names = set(feed_specs)
    io = analyze_block_io(program.global_block, feed_names, fetch_names)
    step = make_step_fn(program.global_block, io, fetch_names)
    state = []
    for n in io["donated"] + io["ro"]:
        v = scope.find_var(n)
        if v is None:
            raise RuntimeError(f"var '{n}' not in scope — run startup/load "
                               f"params before exporting")
        state.append(np.asarray(v))
    n_don = len(io["donated"])

    def infer_fn(*feed_vals):
        feeds = list(feed_vals)
        fetches, _ = step(feeds, [jax.numpy.asarray(s)
                                  for s in state[:n_don]],
                          [jax.numpy.asarray(s) for s in state[n_don:]],
                          jax.random.key(0))
        return tuple(fetches)

    args = [jax.ShapeDtypeStruct(tuple(s), np.dtype(d))
            for n, (s, d) in ((n, feed_specs[n])
                              for n in io["feed_order"])]
    exported = jexport.export(jax.jit(infer_fn))(*args)
    with open(path, "wb") as f:
        f.write(exported.serialize())
    with open(path + ".mlir", "w") as f:
        f.write(exported.mlir_module())
    return {"feed_order": io["feed_order"], "fetch_names": fetch_names}


class StableHLOPredictor:
    """Run a serialized StableHLO artifact (no Program machinery needed)."""

    def __init__(self, path: str):
        from jax import export as jexport

        with open(path, "rb") as f:
            self._exported = jexport.deserialize(f.read())

    def run(self, *inputs):
        return [np.asarray(v) for v in self._exported.call(*inputs)]


def load_stablehlo(path: str) -> StableHLOPredictor:
    return StableHLOPredictor(path)
