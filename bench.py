"""Benchmark entry (driver contract): prints ONE JSON line.

Metric: ResNet-50 ImageNet inference latency, batch 128, fp32 — directly
comparable to the reference's only published numbers
(paddle/contrib/float16/float16_benchmark.md:37-45: 127.02 ms fp32 /
64.52 ms fp16 on 1x V100). vs_baseline = reference fp32 latency / ours
(>1 means faster than the reference).
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

REF_FP32_MS = 127.02  # V100 fp32, float16_benchmark.md:41-45


def main():
    import paddle_tpu as fluid
    from paddle_tpu.models.resnet import build_resnet

    batch = 128
    model = build_resnet(depth=50, class_num=1000, build_optimizer=False)
    infer = model["main"].clone(for_test=True)
    logits = model["logits"].name

    import jax

    exe = fluid.Executor(fluid.TPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    img = rng.rand(batch, 3, 224, 224).astype(np.float32)
    lbl = rng.randint(0, 1000, (batch, 1)).astype(np.int64)
    # Stage the batch on device once: measures compute, not the dev-tunnel's
    # host->device bandwidth (the DataLoader's double-buffer prefetch overlaps
    # that transfer in real training; reference BufferedReader does the same
    # on a side CUDA stream — reader/buffered_reader.cc).
    dev = fluid.TPUPlace().jax_device()
    feed = {"img": jax.device_put(img, dev), "label": jax.device_put(lbl, dev)}

    with fluid.scope_guard(scope):
        exe.run(model["startup"])
        # warmup (compile + cache)
        for _ in range(3):
            out = exe.run(infer, feed=feed, fetch_list=[logits],
                          return_numpy=False)
            out[0].block_until_ready()
        iters = 20
        t0 = time.perf_counter()
        for _ in range(iters):
            out = exe.run(infer, feed=feed, fetch_list=[logits],
                          return_numpy=False)
        out[0].block_until_ready()
        dt_ms = (time.perf_counter() - t0) / iters * 1e3

    print(json.dumps({
        "metric": "resnet50_imagenet_infer_bs128_fp32_ms",
        "value": round(dt_ms, 2),
        "unit": "ms/batch",
        "vs_baseline": round(REF_FP32_MS / dt_ms, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
