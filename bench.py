"""Benchmark entry (driver contract): prints ONE JSON line.

Headline metric: ResNet-50 ImageNet TRAINING throughput (img/s) in bf16 via
the AMP policy — the BASELINE.json north-star metric. The reference publishes
no training numbers (BASELINE.md), so ``vs_baseline`` compares our bf16
INFERENCE latency against the reference's published ResNet50 bs=128 fp16
number (64.52 ms on 1x V100, paddle/contrib/float16/float16_benchmark.md:
41-45) — the only mixed-precision apples-to-apples figure that exists.

MEASUREMENT PROTOCOL (docs/PERF_NOTES.md has the full story; the r4 number
this replaces measured dispatch rate, not compute, and claimed 309% of
peak): every timed section runs K data-dependent iterations INSIDE one
compiled dispatch via ``Executor.run_chained`` (a lax.scan over the step —
while-loop semantics serialize the bodies on-device), ends with a host
fetch (the only hard sync through the axon tunnel), and removes the
dispatch round-trip by differencing two chain lengths:

    per_step = (T(K_long) - T(K_short)) / (K_long - K_short)

Feeds are staged on device once and reused every iteration (the DataLoader
double-buffers real input pipelines; reference BufferedReader does the same
on a side CUDA stream — reader/buffered_reader.cc).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# persistent XLA compile cache: the first bench run pays the compiles,
# subsequent runs (the driver's) reuse them where the backend honors it
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.expanduser("~"), ".cache",
                                   "paddle_tpu", "xla_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

REF_FP16_INFER_MS = 64.52  # V100 fp16 bs=128, float16_benchmark.md:41-45
RESNET50_TRAIN_GFLOP_PER_IMG = 3 * 4.1  # fwd ~4.1 GFLOP @224; bwd ~2x fwd
V5E_BF16_PEAK_TFLOPS = 197.0

PROTOCOL = ("chained-scan per Executor.run_chained: K data-dependent steps "
            "in one dispatch, host fetch sync, per_step=(T_long-T_short)/"
            "(K_long-K_short), min over repeats")


def _device():
    import paddle_tpu as fluid

    return fluid.TPUPlace().jax_device()


def time_chained(exe, program, feed, fetch_list, scope,
                 k_short=2, k_long=10, repeats=3):
    """Seconds per step by the chained protocol (module docstring),
    through the one shared implementation (tuning.chained_step_seconds) —
    bench, xla_sweep, fusion_check and measure_candidates must stay
    number-comparable."""
    from paddle_tpu import tuning

    return tuning.chained_step_seconds(exe, program, feed, fetch_list,
                                       scope, k_short=k_short,
                                       k_long=k_long, repeats=repeats)


def bench_resnet_train(amp: bool, batch=128, k_short=2, k_long=10):
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.models.resnet import build_resnet

    model = build_resnet(depth=50, class_num=1000, amp=amp)
    exe = fluid.Executor(fluid.TPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    dev = _device()
    feed = {"img": jax.device_put(
                rng.rand(batch, 3, 224, 224).astype(np.float32), dev),
            "label": jax.device_put(
                rng.randint(0, 1000, (batch, 1)).astype(np.int64), dev)}
    with fluid.scope_guard(scope):
        exe.run(model["startup"])
        dt = time_chained(exe, model["main"], feed, [model["loss"]], scope,
                          k_short, k_long)
    return batch / dt  # img/s


def bench_resnet_infer(amp: bool, batch=128, k_short=4, k_long=20,
                       fused: bool = False):
    """NOTE on the trajectory (docs/PERF_NOTES.md "The r05 infer
    discontinuity"): r03/r04 infer numbers timed pipelined async
    dispatches; r05 switched to the chained scan but the anti-hoisting
    chain did not engage for for_test programs whose only carried state is
    identity-written batch_norm statistics, so XLA could hoist the body
    and the differenced per-step time was unsound. The chain now engages
    for every non-training program — numbers from this round on are
    serialized per-step compute and NOT comparable to r03-r05."""
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.models.resnet import build_resnet

    from paddle_tpu.contrib import mixed_precision as mp

    model = build_resnet(depth=50, class_num=1000, build_optimizer=False)
    infer = model["main"].clone(for_test=True)
    if amp:
        mp.decorate_program(infer)  # forward-only bf16, no training graph
    exe = fluid.Executor(fluid.TPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    dev = _device()
    feed = {"img": jax.device_put(
                rng.rand(batch, 3, 224, 224).astype(np.float32), dev),
            "label": jax.device_put(
                rng.randint(0, 1000, (batch, 1)).astype(np.int64), dev)}
    logits = model["logits"].name
    prev = fluid.get_flags(["FLAGS_epilogue_fusion"])
    fluid.set_flags({"FLAGS_epilogue_fusion": fused})
    try:
        with fluid.scope_guard(scope):
            exe.run(model["startup"])
            dt = time_chained(exe, infer, feed, [logits], scope,
                              k_short, k_long)
    finally:
        fluid.set_flags(prev)
    return dt * 1e3  # ms/batch


def bench_bert_infer(batch=32, seq_len=512, k_short=2, k_long=8,
                     fused: bool = False):
    """BERT-base forward-only (the epilogue-fusion showcase: every
    q/k/v/out projection and FFN layer carries a mul+bias(+gelu) chain).
    ``fused=True`` runs the identical program under FLAGS_epilogue_fusion
    so the BENCH trajectory records the fused-vs-unfused win per round."""
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.models.bert import BertConfig, build_bert_pretrain

    cfg = BertConfig.base()
    model = build_bert_pretrain(cfg, seq_len=seq_len, amp=True,
                                build_optimizer=False)
    infer = model["main"].clone(for_test=True)
    exe = fluid.Executor(fluid.TPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    dev = _device()
    feed = {
        "src_ids": rng.randint(0, cfg.vocab_size, (batch, seq_len)),
        "pos_ids": np.tile(np.arange(seq_len), (batch, 1)),
        "sent_ids": np.zeros((batch, seq_len)),
        "input_mask": np.ones((batch, seq_len), np.float32),
        "mask_label": np.full((batch, seq_len), -100),
        "next_sent_label": rng.randint(0, 2, (batch, 1)),
    }
    feed["mask_label"][:, ::7] = rng.randint(
        0, cfg.vocab_size, feed["mask_label"][:, ::7].shape)
    for k in ("src_ids", "pos_ids", "sent_ids", "mask_label",
              "next_sent_label"):
        feed[k] = feed[k].astype(np.int64)
    feed = {k: jax.device_put(v, dev) for k, v in feed.items()}
    prev = fluid.get_flags(["FLAGS_epilogue_fusion"])
    fluid.set_flags({"FLAGS_epilogue_fusion": fused})
    try:
        with fluid.scope_guard(scope):
            exe.run(model["startup"])
            dt = time_chained(exe, infer, feed, [model["loss"].name],
                              scope, k_short, k_long)
    finally:
        fluid.set_flags(prev)
    return dt  # s/batch


def bench_bert_train(batch=32, seq_len=512, k_short=2, k_long=8,
                     use_flash=True, auto_remat=False):
    """BERT-base pretraining step. bs=32 fits the 16 GB chip without remat
    (VERDICT r4 reproduced the bs=64 HBM OOM); bs=64 needs
    ``auto_remat=True`` — FLAGS_auto_recompute segments the forward at
    layer boundaries and the memory planner picks the checkpoint set
    (analysis/remat.py; docs/PERF_NOTES.md)."""
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.models.bert import BertConfig, build_bert_pretrain

    prev_flash = fluid.get_flags(["FLAGS_use_flash_attention",
                                  "FLAGS_auto_recompute"])
    fluid.set_flags({"FLAGS_use_flash_attention": use_flash,
                     "FLAGS_auto_recompute": auto_remat})
    try:
        cfg = BertConfig.base()
        model = build_bert_pretrain(cfg, seq_len=seq_len, amp=True)
        exe = fluid.Executor(fluid.TPUPlace())
        scope = fluid.Scope()
        rng = np.random.RandomState(0)
        dev = _device()
        feed = {
            "src_ids": rng.randint(0, cfg.vocab_size, (batch, seq_len)),
            "pos_ids": np.tile(np.arange(seq_len), (batch, 1)),
            "sent_ids": np.zeros((batch, seq_len)),
            "input_mask": np.ones((batch, seq_len), np.float32),
            "mask_label": np.full((batch, seq_len), -100),
            "next_sent_label": rng.randint(0, 2, (batch, 1)),
        }
        feed["mask_label"][:, ::7] = rng.randint(
            0, cfg.vocab_size, feed["mask_label"][:, ::7].shape)
        for k in ("src_ids", "pos_ids", "sent_ids", "mask_label",
                  "next_sent_label"):
            feed[k] = feed[k].astype(np.int64)
        feed = {k: jax.device_put(v, dev) for k, v in feed.items()}
        n_params = 110e6  # BERT-base
        with fluid.scope_guard(scope):
            exe.run(model["startup"])
            dt = time_chained(exe, model["main"], feed, [model["loss"]],
                              scope, k_short, k_long)
    finally:
        fluid.set_flags(prev_flash)
    steps_per_s = 1.0 / dt
    # 6ND for the matmul path plus the attention-score term (QK^T + PV are
    # 4*B*S^2*hidden FLOPs/layer fwd, x3 with backward) which 6ND omits and
    # which is no longer negligible at seq 512.
    attn_flops = 3 * 4 * batch * seq_len**2 * cfg.hidden_size * cfg.num_layers
    tflops = (6 * n_params * batch * seq_len + attn_flops) * steps_per_s / 1e12
    return steps_per_s, tflops, batch, seq_len


def bench_gpt_decode(speculative: bool = False, n_requests: int = 8,
                     max_new: int = 56):
    """GPT-tiny generation tokens/s through the generative serving engine
    (ISSUE 20) — the decode headline. Single-stream latency-bound greedy
    traffic with a shared 12-token prefix, so the number reflects the
    real decode path: prefix-cache admission, chunked prefill, paged-KV
    decode chunks, and (``speculative=True``) k=8 draft-verify chunks
    committing up to 9 tokens per dispatch. Greedy speculative output is
    bit-exact vs plain by construction (tests + the load_check gate
    enforce it), so the two legs are directly comparable. Returns
    ``(tokens_per_s, generation_stats)``."""
    import paddle_tpu as fluid
    import paddle_tpu.unique_name as un
    from paddle_tpu import serving
    from paddle_tpu.models.gpt import GptConfig, build_gpt_generative

    with un.guard():
        net = build_gpt_generative(GptConfig.tiny(), batch_slots=4,
                                   max_seq=128, page_size=8,
                                   prompt_buckets=(8, 16), spec_k=8)
    exe = fluid.Executor(fluid.TPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(net["startup"], scope=scope)
    eng = serving.GenerativeEngine(
        net, scope=scope, executor=exe,
        config=serving.ServingConfig(max_batch=4, queue_depth=64,
                                     deadline_s=0),
        gen_config=serving.GenerationConfig(decode_chunk=2,
                                            speculative=speculative))
    eng.warm_up()
    rng = np.random.RandomState(5)
    shared = rng.randint(1, 128, 12)
    toks, t0 = 0, time.time()
    with eng:
        for i in range(n_requests):
            p = np.concatenate([shared, rng.randint(1, 128, 2 + i % 3)])
            out = eng.submit(p, max_new_tokens=max_new) \
                .result(timeout=600)[0]
            toks += len(out)
    wall = time.time() - t0
    stats = eng.generation_stats()
    if not eng.accounting()["exact"] or stats["decode_recompiles"]:
        raise RuntimeError("decode bench integrity: accounting inexact "
                           "or warm recompiles observed")
    return toks / wall if wall > 0 else 0.0, stats


def main():
    """Sections run independently: one that RAISES never loses the others
    and the JSON line still prints (a section that hangs is still fatal —
    only the external driver's timeout can reap that)."""
    from paddle_tpu import monitor

    extra = {"protocol": PROTOCOL}

    # compile visibility for the BENCH trajectory: every compile the bench
    # pays is recorded via the monitor hook API (docs/OBSERVABILITY.md) so
    # a perf regression can be split into "compute got slower" vs "we
    # started recompiling"
    compile_log = []
    hook = monitor.add_hook(on_compile=lambda rec: compile_log.append(rec))

    def section(key, fn):
        t0 = time.time()
        try:
            val = fn()
            extra[f"{key}_bench_seconds"] = round(time.time() - t0, 1)
            return val
        except Exception as e:  # record, keep going
            extra[f"{key}_error"] = f"{type(e).__name__}: {e}"[:200]
            return None

    train_bf16 = section("resnet50_train_bf16",
                         lambda: bench_resnet_train(amp=True))
    infer_bf16_ms = section("resnet50_infer_bf16",
                            lambda: bench_resnet_infer(amp=True))
    # fused legs (FLAGS_epilogue_fusion): the MFU-gap round's win, recorded
    # per trajectory point. Training legs stay unfused BY DESIGN — the
    # fusion pass refuses backward-carrying programs (grad ops read the
    # epilogue intermediates); extra["fusion"] records that refusal
    # honestly instead of timing a no-op leg.
    infer_fused_ms = section("resnet50_infer_bf16_fused",
                             lambda: bench_resnet_infer(amp=True,
                                                        fused=True))
    bert_infer_s = section("bert_base_infer_bf16",
                           lambda: bench_bert_infer(fused=False))
    bert_infer_fused_s = section("bert_base_infer_bf16_fused",
                                 lambda: bench_bert_infer(fused=True))
    bert = section("bert", bench_bert_train)
    # the leg r5 said we could not reach: bs=64 needs auto-remat to fit
    # the 16 GB chip (bs=32 peak ~2x'd by doubling the batch)
    bert64 = section("bert_bs64_remat",
                     lambda: bench_bert_train(batch=64, auto_remat=True))
    # decode headline (ISSUE 20): tokens/s through the generative engine,
    # plain and speculative, plus the prefix-cache hit stats
    gpt_dec = section("gpt_tiny_decode", lambda: bench_gpt_decode(False))
    gpt_spec = section("gpt_tiny_decode_spec",
                       lambda: bench_gpt_decode(True))
    if gpt_dec is not None:
        tps_plain, dec_stats = gpt_dec
        extra["gpt_tiny_decode_tokens_per_s"] = round(tps_plain, 1)
        extra["prefix_cache"] = dec_stats["prefix_cache"]
    if gpt_spec is not None:
        tps_spec, spec_stats = gpt_spec
        extra["gpt_tiny_decode_spec_tokens_per_s"] = round(tps_spec, 1)
        extra["gpt_tiny_decode_spec"] = {
            "k": spec_stats["speculative"]["k"],
            "verify_chunks": spec_stats["speculative"]["chunks"],
            "accepted_tokens":
                spec_stats["speculative"]["accepted_tokens"],
        }
        if gpt_dec is not None and tps_plain > 0:
            extra["gpt_tiny_decode_spec_speedup"] = round(
                tps_spec / tps_plain, 3)

    if train_bf16 is not None:
        train_tflops = train_bf16 * RESNET50_TRAIN_GFLOP_PER_IMG / 1e3
        extra["resnet50_train_bf16_tflops"] = round(train_tflops, 1)
        extra["resnet50_train_mfu_vs_v5e_peak"] = round(
            train_tflops / V5E_BF16_PEAK_TFLOPS, 3)
    if infer_bf16_ms is not None:
        extra["resnet50_infer_bs128_bf16_ms"] = round(infer_bf16_ms, 2)
        extra["ref_v100_fp16_infer_bs128_ms"] = REF_FP16_INFER_MS
        # r03-r05 infer values are NOT comparable: two generations of
        # broken serialization (async-dispatch pipelining, then a hoisted
        # scan body) — docs/PERF_NOTES.md "The r05 infer discontinuity"
        extra["infer_protocol"] = (
            "chained-v2: anti-hoisting chain forced for all non-training "
            "programs; r03-r05 infer points measured hoisted/pipelined "
            "bodies and are not comparable")
    if infer_fused_ms is not None:
        extra["resnet50_infer_bs128_bf16_fused_ms"] = round(infer_fused_ms,
                                                            2)
        if infer_bf16_ms:
            extra["resnet50_infer_fused_speedup"] = round(
                infer_bf16_ms / infer_fused_ms, 3)
    if bert_infer_s is not None:
        extra["bert_base_infer_bf16_ms"] = round(bert_infer_s * 1e3, 1)
    if bert_infer_fused_s is not None:
        extra["bert_base_infer_bf16_fused_ms"] = round(
            bert_infer_fused_s * 1e3, 1)
        if bert_infer_s:
            extra["bert_infer_fused_speedup"] = round(
                bert_infer_s / bert_infer_fused_s, 3)
    monitor.remove_hook(hook)
    extra["monitor"] = {
        "compiles": len(compile_log),
        "recompiles": monitor.recompile_count(),
        "compile_seconds_total": round(sum(
            (rec.trace_lower_s or 0) + (rec.compile_s or 0)
            for rec in compile_log), 2),
        "chained_iterations": int(monitor.metric_value(
            "executor_chained_iterations_total") or 0),
        "steps": {p: int(monitor.metric_value("executor_steps_total",
                                              path=p) or 0)
                  for p in ("run", "chained")},
    }

    # cost-model accounting (analysis/cost_model.py, this round): model
    # FLOPs derived from the programs' infer_shape metadata, reported
    # next to the hand-derived analytic counts (docs/PERF_NOTES.md "Cost
    # model"; the trace gate asserts the ratios stay within 10%). The
    # legacy headline keys keep their historical 1/MAC ResNet constant
    # for trajectory continuity; cost_model.* uses 2 FLOPs per MAC
    # everywhere (the 6ND convention the BERT legs always used).
    def _cost_section():
        import paddle_tpu.unique_name as un
        from paddle_tpu.analysis.cost_model import estimate_cost
        from paddle_tpu.models.bert import BertConfig, build_bert_pretrain
        from paddle_tpu.models.resnet import build_resnet

        peak = V5E_BF16_PEAK_TFLOPS
        cm = {"convention": "2 FLOPs per multiply-add (6ND)"}
        with un.guard():
            rn = build_resnet(depth=50, class_num=1000, amp=True)
        rep = estimate_cost(rn["main"], batch_size=128)
        per_img = rep.flops_total / 128
        leg = {"gflops_per_img": round(per_img / 1e9, 2),
               "analytic_gflops_per_img": 24.55,
               "vs_analytic_ratio": round(per_img / 24.55e9, 3),
               "flops_per_byte": round(rep.flops_per_byte, 1)}
        if train_bf16 is not None:
            tf = train_bf16 * per_img / 1e12
            leg["achieved_tflops"] = round(tf, 1)
            leg["mfu"] = round(tf / peak, 3)
        cm["resnet50_train_bs128"] = leg
        if bert is not None:
            b_steps, _tf, b_bs, b_sl = bert
            cfg = BertConfig.base()
            with un.guard():
                bm = build_bert_pretrain(cfg, seq_len=b_sl, amp=True)
            rep_b = estimate_cost(bm["main"], batch_size=b_bs)
            analytic = (6 * 110e6 * b_bs * b_sl
                        + 3 * 4 * b_bs * b_sl ** 2
                        * cfg.hidden_size * cfg.num_layers)
            tf_b = rep_b.flops_total * b_steps / 1e12
            cm[f"bert_base_train_bs{b_bs}"] = {
                "tflops_per_step": round(rep_b.flops_total / 1e12, 3),
                "analytic_tflops_per_step": round(analytic / 1e12, 3),
                "vs_analytic_ratio": round(rep_b.flops_total / analytic,
                                           3),
                "achieved_tflops": round(tf_b, 1),
                "mfu": round(tf_b / peak, 3),
                "flops_per_byte": round(rep_b.flops_per_byte, 1)}
        return cm

    section("cost_model", lambda: extra.update(
        {"cost_model": _cost_section()}))

    if bert is not None:
        bert_steps, bert_tflops, bert_bs, bert_sl = bert
        extra["bert_base_train_bf16_steps_per_s"] = round(bert_steps, 3)
        extra["bert_base_train_bf16_tflops"] = round(bert_tflops, 1)
        extra["bert_base_train_mfu_vs_v5e_peak"] = round(
            bert_tflops / V5E_BF16_PEAK_TFLOPS, 3)
        extra["bert_batch"], extra["bert_seq_len"] = bert_bs, bert_sl
    if bert64 is not None:
        b64_steps, b64_tflops, b64_bs, b64_sl = bert64
        extra["bert_bs64_remat_train_bf16_steps_per_s"] = round(b64_steps, 3)
        extra["bert_bs64_remat_train_bf16_tflops"] = round(b64_tflops, 1)
        extra["bert_bs64_remat_train_mfu_vs_v5e_peak"] = round(
            b64_tflops / V5E_BF16_PEAK_TFLOPS, 3)
        extra["bert_bs64_remat_batch"] = b64_bs
        extra["bert_bs64_remat_seq_len"] = b64_sl
    # memory trajectory (this round on): auto-remat activity + the memory
    # planner's predicted peaks for the last transformed program (the bs=64
    # BERT leg), so BENCH_*.json tracks memory alongside throughput
    # epilogue-fusion + autotuner trajectory: chains fused per epilogue
    # kind during the fused legs, plus the documented training-program
    # refusal (static, no timing cost)
    def _fusion_section():
        import paddle_tpu.unique_name as un
        from paddle_tpu.analysis.epilogue_fusion import fuse_epilogues
        from paddle_tpu.models.resnet import build_resnet

        fam = monitor.get_registry().to_dict().get(
            "fusion_ops_fused_total", {})
        by_kind = {v["labels"].get("epilogue", "?"): int(v["value"])
                   for v in fam.get("values", ())}
        with un.guard():
            train = build_resnet(depth=50, class_num=1000, amp=True)
        dec = fuse_epilogues(train["main"],
                             fetch_names=[train["loss"].name])
        return {
            "programs_applied": int(monitor.metric_value(
                "fusion_programs_total", outcome="applied") or 0),
            "programs_refused": int(monitor.metric_value(
                "fusion_programs_total", outcome="refused") or 0),
            "chains_by_epilogue": by_kind,
            "train_program_decision": {"applied": dec.applied,
                                       "reason": dec.reason},
        }

    section("fusion", lambda: extra.update({"fusion": _fusion_section()}))
    extra["autotune"] = {
        "hits": int(monitor.metric_value("autotune_hits_total") or 0),
        "misses": int(monitor.metric_value("autotune_misses_total") or 0),
        "trials": int(monitor.metric_value("autotune_trials_total") or 0),
    }
    extra["remat"] = {
        "programs_applied": int(monitor.metric_value(
            "remat_programs_total", outcome="applied") or 0),
        "programs_refused": int(monitor.metric_value(
            "remat_programs_total", outcome="refused") or 0),
        "segments_inserted": int(monitor.metric_value(
            "remat_segments_inserted_total") or 0),
        "predicted_peak_bytes_plain": int(monitor.metric_value(
            "remat_predicted_peak_bytes", variant="plain") or 0),
        "predicted_peak_bytes_remat": int(monitor.metric_value(
            "remat_predicted_peak_bytes", variant="remat") or 0),
    }

    print(json.dumps({
        "metric": "resnet50_train_bf16_img_per_s",
        "value": round(train_bf16, 1) if train_bf16 is not None else -1,
        "unit": "img/s/chip",
        "vs_baseline": (round(REF_FP16_INFER_MS / infer_bf16_ms, 3)
                        if infer_bf16_ms else -1),
        "extra": extra,
    }))


if __name__ == "__main__":
    sys.exit(main())
