"""Benchmark entry (driver contract): prints ONE JSON line.

Headline metric: ResNet-50 ImageNet TRAINING throughput (img/s) in bf16 via
the AMP policy — the BASELINE.json north-star metric ("ResNet-50 images/sec/
chip"). The reference publishes no training numbers (BASELINE.md), so
``vs_baseline`` compares our bf16 INFERENCE latency against the reference's
published ResNet50 bs=128 fp16 number (64.52 ms on 1x V100,
paddle/contrib/float16/float16_benchmark.md:41-45) — the only mixed-precision
apples-to-apples figure that exists. ``extra`` carries bf16 inference ms,
BERT-base steps/s, achieved TFLOP/s + MFU vs v5e bf16 peak, and per-section
wall times (or ``<key>_error`` strings for sections that raised).

Feeds are staged on device once: measures compute, not the dev-tunnel's
host->device bandwidth (the DataLoader's double-buffer prefetch overlaps that
transfer in real training; reference BufferedReader does the same on a side
CUDA stream — reader/buffered_reader.cc).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# persistent XLA compile cache: the first bench run pays the ~3min/section
# compiles through the dev tunnel, subsequent runs (the driver's) reuse them
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.expanduser("~"), ".cache",
                                   "paddle_tpu", "xla_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

REF_FP16_INFER_MS = 64.52  # V100 fp16 bs=128, float16_benchmark.md:41-45
RESNET50_TRAIN_GFLOP_PER_IMG = 3 * 4.1  # fwd ~4.1 GFLOP @224; bwd ~2x fwd
V5E_BF16_PEAK_TFLOPS = 197.0


def _device():
    import paddle_tpu as fluid

    return fluid.TPUPlace().jax_device()


def _time_steps(run_fn, warmup, iters, scope=None):
    """Dispatch all iters, then block on the last call's fetches AND (for
    training) the final scope state — blocking on the loss alone is not
    enough through the async dispatch pipeline to prove the updates landed."""
    import jax

    def drain(out):
        jax.block_until_ready(out)
        if scope is not None:
            jax.block_until_ready(list(scope.vars.values()))

    for _ in range(warmup):
        out = run_fn()
    drain(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = run_fn()
    drain(out)
    return (time.perf_counter() - t0) / iters


def bench_resnet_train(amp: bool, batch=128, iters=10):
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.models.resnet import build_resnet

    model = build_resnet(depth=50, class_num=1000, amp=amp)
    exe = fluid.Executor(fluid.TPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    dev = _device()
    feed = {"img": jax.device_put(
                rng.rand(batch, 3, 224, 224).astype(np.float32), dev),
            "label": jax.device_put(
                rng.randint(0, 1000, (batch, 1)).astype(np.int64), dev)}
    with fluid.scope_guard(scope):
        exe.run(model["startup"])
        dt = _time_steps(
            lambda: exe.run(model["main"], feed=feed,
                            fetch_list=[model["loss"]], return_numpy=False),
            warmup=3, iters=iters, scope=scope)
    return batch / dt  # img/s


def bench_resnet_infer(amp: bool, batch=128, iters=20):
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.models.resnet import build_resnet

    from paddle_tpu.contrib import mixed_precision as mp

    model = build_resnet(depth=50, class_num=1000, build_optimizer=False)
    infer = model["main"].clone(for_test=True)
    if amp:
        mp.decorate_program(infer)  # forward-only bf16, no training graph
    exe = fluid.Executor(fluid.TPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    dev = _device()
    feed = {"img": jax.device_put(
                rng.rand(batch, 3, 224, 224).astype(np.float32), dev),
            "label": jax.device_put(
                rng.randint(0, 1000, (batch, 1)).astype(np.int64), dev)}
    logits = model["logits"].name
    with fluid.scope_guard(scope):
        exe.run(model["startup"])
        dt = _time_steps(
            lambda: exe.run(infer, feed=feed, fetch_list=[logits],
                            return_numpy=False),
            warmup=3, iters=iters)
    return dt * 1e3  # ms/batch


def bench_bert_train(batch=64, seq_len=512, iters=10):
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.models.bert import BertConfig, build_bert_pretrain

    cfg = BertConfig.base()
    model = build_bert_pretrain(cfg, seq_len=seq_len, amp=True)
    exe = fluid.Executor(fluid.TPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    dev = _device()
    feed = {
        "src_ids": rng.randint(0, cfg.vocab_size, (batch, seq_len)),
        "pos_ids": np.tile(np.arange(seq_len), (batch, 1)),
        "sent_ids": np.zeros((batch, seq_len)),
        "input_mask": np.ones((batch, seq_len), np.float32),
        "mask_label": np.full((batch, seq_len), -100),
        "next_sent_label": rng.randint(0, 2, (batch, 1)),
    }
    feed["mask_label"][:, ::7] = rng.randint(
        0, cfg.vocab_size, feed["mask_label"][:, ::7].shape)
    for k in ("src_ids", "pos_ids", "sent_ids", "mask_label",
              "next_sent_label"):
        feed[k] = feed[k].astype(np.int64)
    feed = {k: jax.device_put(v, dev) for k, v in feed.items()}
    n_params = 110e6  # BERT-base
    with fluid.scope_guard(scope):
        exe.run(model["startup"])
        dt = _time_steps(
            lambda: exe.run(model["main"], feed=feed,
                            fetch_list=[model["loss"]], return_numpy=False),
            warmup=2, iters=iters, scope=scope)
    steps_per_s = 1.0 / dt
    # 6ND for the matmul path plus the attention-score term (QK^T + PV are
    # 4*B*S^2*hidden FLOPs/layer fwd, x3 with backward) which 6ND omits and
    # which is no longer negligible at seq 512.
    attn_flops = 3 * 4 * batch * seq_len**2 * cfg.hidden_size * cfg.num_layers
    tflops = (6 * n_params * batch * seq_len + attn_flops) * steps_per_s / 1e12
    return steps_per_s, tflops, batch, seq_len


def main():
    """Sections run independently: one that RAISES never loses the others
    and the JSON line still prints (a section that hangs is still fatal —
    only the external driver's timeout can reap that). Compiles through the
    axon dev tunnel take ~2-3 min per section and the remote backend
    ignores the local persistent cache, so the suite is kept to the three
    numbers that matter: the headline training throughput, the only
    reference-comparable inference figure, and BERT steps/s."""
    extra = {}

    def section(key, fn):
        t0 = time.time()
        try:
            val = fn()
            extra[f"{key}_bench_seconds"] = round(time.time() - t0, 1)
            return val
        except Exception as e:  # record, keep going
            extra[f"{key}_error"] = f"{type(e).__name__}: {e}"[:200]
            return None

    train_bf16 = section("resnet50_train_bf16",
                         lambda: bench_resnet_train(amp=True))
    infer_bf16_ms = section("resnet50_infer_bf16",
                            lambda: bench_resnet_infer(amp=True))
    bert = section("bert", bench_bert_train)

    if train_bf16 is not None:
        train_tflops = train_bf16 * RESNET50_TRAIN_GFLOP_PER_IMG / 1e3
        extra["resnet50_train_bf16_tflops"] = round(train_tflops, 1)
        extra["resnet50_train_mfu_vs_v5e_peak"] = round(
            train_tflops / V5E_BF16_PEAK_TFLOPS, 3)
    if infer_bf16_ms is not None:
        extra["resnet50_infer_bs128_bf16_ms"] = round(infer_bf16_ms, 2)
        extra["ref_v100_fp16_infer_bs128_ms"] = REF_FP16_INFER_MS
    if bert is not None:
        bert_steps, bert_tflops, bert_bs, bert_sl = bert
        extra["bert_base_train_bf16_steps_per_s"] = round(bert_steps, 2)
        extra["bert_base_train_bf16_tflops"] = round(bert_tflops, 1)
        extra["bert_base_train_mfu_vs_v5e_peak"] = round(
            bert_tflops / V5E_BF16_PEAK_TFLOPS, 3)
        extra["bert_batch"], extra["bert_seq_len"] = bert_bs, bert_sl

    print(json.dumps({
        "metric": "resnet50_train_bf16_img_per_s",
        "value": round(train_bf16, 1) if train_bf16 is not None else -1,
        "unit": "img/s/chip",
        "vs_baseline": (round(REF_FP16_INFER_MS / infer_bf16_ms, 3)
                        if infer_bf16_ms else -1),
        "extra": extra,
    }))


if __name__ == "__main__":
    sys.exit(main())
