#!/usr/bin/env bash
# CI driver (the paddle_build.sh role, reference paddle/scripts/paddle_build.sh):
#   ci/run_ci.sh [fast|full|tpu]
#
# fast: import check + CPU unit tests (8 virtual devices, what the repo's
#       conftest configures)
# full: fast + the multichip dry-run the round driver executes
# tpu : the on-accelerator smoke suite (needs a real chip)
set -euo pipefail
cd "$(dirname "$0")/.."
MODE="${1:-fast}"

echo "== import check"
JAX_PLATFORMS=cpu python -c "
import paddle_tpu
print('ops registered:', len(paddle_tpu.op_registry.all_ops()))
print('version:', paddle_tpu.__version__)"

echo "== static program lint pipeline (full pass-manager run over the model"
echo "   zoo: verifier + PT700s/710s/720s; errors and non-allowlisted"
echo "   dead-code findings gate; JSON report is the CI artifact)"
JAX_PLATFORMS=cpu python tools/lint_program.py --zoo \
  --json "${CI_ARTIFACT_DIR:-.}/ci_lint_report.json" | tail -20

echo "== concurrency lint gate (analysis/concurrency: lock inventory +"
echo "   lock-order graph over the whole package; PT800 cycles, PT801"
echo "   blocking-under-lock and PT802 unguarded cross-thread attrs gate"
echo "   unless allowlisted with a reason; JSON report is the CI artifact"
echo "   — the fleet-chaos leg later merges its runtime lock_witness"
echo "   section into the same file)"
JAX_PLATFORMS=cpu python tools/lint_concurrency.py \
  --json "${CI_ARTIFACT_DIR:-.}/ci_concurrency_report.json"
echo "== concurrency lint negative control (broken fixtures, allowlist"
echo "   off: the gate must FAIL on all of PT800/PT801/PT802)"
CONC_NEG_LOG="${CI_ARTIFACT_DIR:-.}/ci_concurrency_negative.log"
if JAX_PLATFORMS=cpu python tools/lint_concurrency.py \
     --negative-control > "$CONC_NEG_LOG" 2>&1; then
  echo "lint_concurrency did NOT fail on the broken fixtures" >&2
  exit 1
fi
# non-zero exit must be the gate tripping, not the linter crashing
if ! grep -q -- "-> FAIL" "$CONC_NEG_LOG"; then
  echo "concurrency negative control exited non-zero WITHOUT tripping the gate:" >&2
  tail -20 "$CONC_NEG_LOG" >&2
  exit 1
fi

echo "== numerics lint gate (analysis/numerics: interval + dtype-precision"
echo "   flow over the model zoo incl. QAT-transformed variants; PT900"
echo "   broken quant pairing and PT902 overflowing casts are errors,"
echo "   PT901/PT903/PT904/PT905 warnings gate unless allowlisted; PT906"
echo "   is the int8 quantizability work-list; JSON report is the CI"
echo "   artifact)"
JAX_PLATFORMS=cpu python tools/lint_numerics.py \
  --json "${CI_ARTIFACT_DIR:-.}/ci_numerics_report.json" | tail -12
echo "== numerics lint negative control (broken fixtures, allowlist off:"
echo "   the gate must FAIL on all of PT900..PT905)"
NUM_NEG_LOG="${CI_ARTIFACT_DIR:-.}/ci_numerics_negative.log"
if JAX_PLATFORMS=cpu python tools/lint_numerics.py \
     --negative-control > "$NUM_NEG_LOG" 2>&1; then
  echo "lint_numerics did NOT fail on the broken fixtures" >&2
  exit 1
fi
# non-zero exit must be the gate tripping, not the linter crashing
if ! grep -q -- "-> FAIL" "$NUM_NEG_LOG"; then
  echo "numerics negative control exited non-zero WITHOUT tripping the gate:" >&2
  tail -20 "$NUM_NEG_LOG" >&2
  exit 1
fi

echo "== numerics witness cross-check (FLAGS_numerics_witness=1: jitted"
echo "   per-var abs-max/min/max + nonfinite taps over short train+infer"
echo "   runs of the zoo; every observed value must sit INSIDE its proven"
echo "   static interval — tolerance-free containment, the lock-witness"
echo "   idiom — and observed abs-max feeds PT906 calibration into"
echo "   ci_numerics_report.json)"
JAX_PLATFORMS=cpu python tools/lint_numerics.py --witness \
  --json "${CI_ARTIFACT_DIR:-.}/ci_numerics_report.json" | tail -8

echo "== op-registry conformance audit (ops without a lower rule gate)"
JAX_PLATFORMS=cpu python tools/audit_registry.py --strict \
  --json-file "${CI_ARTIFACT_DIR:-.}/ci_registry_audit.json" > /dev/null
JAX_PLATFORMS=cpu python tools/audit_registry.py --untested | tail -3

echo "== peak-memory plan + PT5xx liveness gate (JSON report is the CI artifact)"
JAX_PLATFORMS=cpu python tools/mem_report.py --check \
  --json "${CI_ARTIFACT_DIR:-.}/ci_mem_report.json"

echo "== per-chip memory plan gate (analysis/sharding_check: dp=8 ZeRO-1"
echo "   spec propagation; per-chip peaks must fit the HBM budget, and the"
echo "   static estimate must match the MEASURED live-sharding state bytes"
echo "   of a dp-sharded zoo model within 10% — multichip dryrun)"
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python tools/mem_report.py --mesh dp=8 --specs zero1 --check \
  --validate-live --hbm-budget-mb 15872 \
  --json "${CI_ARTIFACT_DIR:-.}/ci_mem_sharded_report.json" | tail -6

echo "== executor metrics + recompile gate (paddle_tpu.monitor; JSON artifact)"
JAX_PLATFORMS=cpu python tools/metrics_report.py --check \
  --json "${CI_ARTIFACT_DIR:-.}/ci_metrics_report.json"
echo "== recompile tripwire negative control (the gate must FAIL here)"
FORCED_LOG="${CI_ARTIFACT_DIR:-.}/ci_forced_recompile.log"
if JAX_PLATFORMS=cpu python tools/metrics_report.py --check \
     --force-recompile 3 > "$FORCED_LOG" 2>&1; then
  echo "metrics_report --check did NOT fail on a forced-recompile scenario" >&2
  exit 1
fi
# non-zero exit must be the gate tripping, not the scenario crashing
if ! grep -q -- "-> FAIL" "$FORCED_LOG"; then
  echo "forced-recompile control exited non-zero WITHOUT tripping the gate:" >&2
  tail -20 "$FORCED_LOG" >&2
  exit 1
fi

echo "== auto-remat gate (analysis/remat.py: BERT-base predicted peak must"
echo "   drop >=30%, negative control: flag off => zero segments)"
JAX_PLATFORMS=cpu python tools/remat_check.py --check \
  --json "${CI_ARTIFACT_DIR:-.}/ci_remat_report.json"

echo "== XLA compile-option sweep (FLAGS_xla_options plumbing; ranked JSON)"
JAX_PLATFORMS=cpu python tools/xla_sweep.py --ci \
  --json "${CI_ARTIFACT_DIR:-.}/ci_xla_sweep.json" | tail -4

echo "== epilogue-fusion + persistent-autotuner gate (analysis/epilogue_fusion,"
echo "   paddle_tpu.tuning: fused MLP/BERT-tiny/ResNet-tiny legs must match"
echo "   unfused bit-exactly on the dense route and not be slower on the"
echo "   chained-scan protocol; fused programs stay lint-clean; autotune"
echo "   round-trip: a measure subprocess populates the cost DB, a FRESH"
echo "   use-mode subprocess compiles straight to the best config with zero"
echo "   re-trials)"
JAX_PLATFORMS=cpu python tools/fusion_check.py --check \
  --json "${CI_ARTIFACT_DIR:-.}/ci_fusion_report.json" | tail -8
echo "== fusion kill-switch control (FLAGS_epilogue_fusion=0 must show zero"
echo "   fused ops and a bit-exact baseline)"
JAX_PLATFORMS=cpu python tools/fusion_check.py --negative-control | tail -3

echo "== chaos gate (paddle_tpu.resilience: kill-mid-checkpoint + transient"
echo "   compile faults must resume from the last verified checkpoint)"
JAX_PLATFORMS=cpu python tools/chaos_check.py --check \
  --json "${CI_ARTIFACT_DIR:-.}/ci_chaos_report.json"
echo "== chaos negative control (retries disabled: the gate must FAIL here)"
CHAOS_NEG_LOG="${CI_ARTIFACT_DIR:-.}/ci_chaos_negative.log"
if JAX_PLATFORMS=cpu python tools/chaos_check.py --check \
     --negative-control > "$CHAOS_NEG_LOG" 2>&1; then
  echo "chaos_check --check did NOT fail with retries disabled" >&2
  exit 1
fi
# non-zero exit must be the gate tripping, not the harness crashing
if ! grep -q -- "-> FAIL" "$CHAOS_NEG_LOG"; then
  echo "chaos negative control exited non-zero WITHOUT tripping the gate:" >&2
  tail -20 "$CHAOS_NEG_LOG" >&2
  exit 1
fi

echo "== serving load gate (paddle_tpu.serving: under injected overload,"
echo "   compile faults and one watchdog-diagnosed hang, every submitted"
echo "   request reaches exactly one terminal outcome; p50/p99 latency"
echo "   histogram is the artifact. --decode adds the generative legs: a"
echo "   GPT-tiny multi-thread generation burst with exact accounting,"
echo "   zero warm recompiles and tokens/s + inter-token p50/p99 in the"
echo "   artifact, a chaos sub-leg killing one in-flight batch — every"
echo "   affected stream must settle with a typed outcome — plus the"
echo "   ISSUE 20 legs: a shared-prefix burst (prefix hits > 0, warm"
echo "   first-token faster than cold, hit ratio + first-token p99 in"
echo "   the artifact) and a speculative leg (greedy output bit-exact vs"
echo "   non-speculative at >= 1.5x tokens/s, acceptance histogram"
echo "   present)"
JAX_PLATFORMS=cpu python tools/load_check.py --ci --decode \
  --json "${CI_ARTIFACT_DIR:-.}/ci_serving_report.json" | tail -13
echo "== serving negative control (shedding disabled, prefix cache off —"
echo "   hit counters must stay zero — and speculation off — no"
echo "   acceptance histogram may exist: the gate must FAIL)"
SERVING_NEG_LOG="${CI_ARTIFACT_DIR:-.}/ci_serving_negative.log"
if JAX_PLATFORMS=cpu python tools/load_check.py --ci --decode \
     --negative-control > "$SERVING_NEG_LOG" 2>&1; then
  echo "load_check --ci did NOT fail with shedding/prefix/spec disabled" >&2
  exit 1
fi
# non-zero exit must be the gate tripping, not the harness crashing
if ! grep -q -- "-> FAIL" "$SERVING_NEG_LOG"; then
  echo "serving negative control exited non-zero WITHOUT tripping the gate:" >&2
  tail -20 "$SERVING_NEG_LOG" >&2
  exit 1
fi

echo "== fleet serving gate (paddle_tpu.serving.fleet: two replica PROCESSES"
echo "   behind the load-aware router, one SIGTERMed mid-burst — the fleet"
echo "   sheds nothing it admitted, every request reaches exactly one outcome"
echo "   fleet-wide, p50/p99 end-to-end latency recorded; a cold replica"
echo "   restarted with the warm-start AOT executable cache must report"
echo "   measurably faster time-to-ready than its cold baseline. Then the"
echo "   telemetry-plane leg: fleet p50/p99 assembled from SCRAPED per-"
echo "   replica /metrics via the exact histogram merge and cross-checked"
echo "   against the router ledger, SLO burn state flips to burning under"
echo "   injected stalled batches and recovers, the per-tenant ledger"
echo "   reconciles exactly, exported exemplar trace ids resolve to"
echo "   recorded traces, and a corrupt-/metrics target degrades typed"
echo "   (stale-marked, counted) with zero aggregator crashes)"
JAX_PLATFORMS=cpu python tools/load_check.py --ci --fleet \
  --log-dir "${CI_ARTIFACT_DIR:-.}" \
  --json "${CI_ARTIFACT_DIR:-.}/ci_fleet_report.json" | tail -12
echo "== fleet negative control (router drain honoring + unadmitted retry"
echo "   disabled: the kill scenario must FAIL the gate)"
FLEET_NEG_LOG="${CI_ARTIFACT_DIR:-.}/ci_fleet_negative.log"
if JAX_PLATFORMS=cpu python tools/load_check.py --ci --fleet \
     --negative-control --log-dir "${CI_ARTIFACT_DIR:-.}" \
     > "$FLEET_NEG_LOG" 2>&1; then
  echo "load_check --fleet did NOT fail with router drain disabled" >&2
  exit 1
fi
# non-zero exit must be the gate tripping, not the harness crashing
if ! grep -q -- "-> FAIL" "$FLEET_NEG_LOG"; then
  echo "fleet negative control exited non-zero WITHOUT tripping the gate:" >&2
  tail -20 "$FLEET_NEG_LOG" >&2
  exit 1
fi

echo "== fleet self-healing gate (supervisor + bisection + wire chaos: under"
echo "   injected drop/stall/corrupt wire faults a stalling replica is ejected"
echo "   by the router's transport breaker and unadmitted faults retry on the"
echo "   sibling; a poison request co-batched with innocents is isolated by"
echo "   bisection (innocents complete bit-exact, culprit typed PoisonRequest,"
echo "   repeat offender quarantined); a SIGKILLed replica restarts warm under"
echo "   the same id within its backoff budget; a forced crash loop retires"
echo "   with a typed ReplicaCrashLoop). Runs with FLAGS_lock_witness=1:"
echo "   zero runtime lock-order cycles and every observed edge predicted"
echo "   by the static graph also gate; the runtime lock_witness section"
echo "   (wait/hold histograms per named lock) lands in"
echo "   ci_concurrency_report.json"
JAX_PLATFORMS=cpu python tools/load_check.py --ci --fleet-chaos \
  --lock-witness \
  --concurrency-json "${CI_ARTIFACT_DIR:-.}/ci_concurrency_report.json" \
  --log-dir "${CI_ARTIFACT_DIR:-.}" \
  --json "${CI_ARTIFACT_DIR:-.}/ci_fleet_chaos_report.json" | tail -10
echo "== fleet self-healing negative control (supervisor restarts + bisection"
echo "   disabled: innocents must die with the poison and the killed replica"
echo "   must stay dead — the gate must FAIL)"
FLEET_CHAOS_NEG_LOG="${CI_ARTIFACT_DIR:-.}/ci_fleet_chaos_negative.log"
if JAX_PLATFORMS=cpu python tools/load_check.py --ci --fleet-chaos \
     --negative-control --log-dir "${CI_ARTIFACT_DIR:-.}" \
     > "$FLEET_CHAOS_NEG_LOG" 2>&1; then
  echo "load_check --fleet-chaos did NOT fail with self-healing disabled" >&2
  exit 1
fi
# non-zero exit must be the gate tripping, not the harness crashing
if ! grep -q -- "-> FAIL" "$FLEET_CHAOS_NEG_LOG"; then
  echo "fleet-chaos negative control exited non-zero WITHOUT tripping the gate:" >&2
  tail -20 "$FLEET_CHAOS_NEG_LOG" >&2
  exit 1
fi

echo "== fleet control-loop gate (FleetAutoscaler + tenant fair-share: a"
echo "   hot-tenant flood is shed typed tenant_quota while innocent tenants"
echo "   keep their SLO, the shed storm burns the SLO budget and the"
echo "   autoscaler scales OUT a second replica warm through the fleet-shared"
echo "   AOT cache AND the fleet-shared autotune CostDatabase (faster"
echo "   time-to-ready than the cold baseline, autotune hits with zero"
echo "   re-trials), refusals at the max are typed+metered, calm scales back"
echo "   IN strictly via preemption-drain with an exact exit ledger, and the"
echo "   floor holds typed at_min_replicas — fleet accounting exact"
echo "   throughout)"
JAX_PLATFORMS=cpu python tools/load_check.py --ci --autoscale \
  --log-dir "${CI_ARTIFACT_DIR:-.}" \
  --json "${CI_ARTIFACT_DIR:-.}/ci_autoscale_report.json" | tail -14
echo "== fleet control-loop negative control (no autoscaler, no tenant"
echo "   quotas: sustained hot pressure goes unanswered and the hot tenant"
echo "   is never shed typed — the gate must FAIL)"
AUTOSCALE_NEG_LOG="${CI_ARTIFACT_DIR:-.}/ci_autoscale_negative.log"
if JAX_PLATFORMS=cpu python tools/load_check.py --ci --autoscale \
     --negative-control --log-dir "${CI_ARTIFACT_DIR:-.}" \
     > "$AUTOSCALE_NEG_LOG" 2>&1; then
  echo "load_check --autoscale did NOT fail without the control loop" >&2
  exit 1
fi
# non-zero exit must be the gate tripping, not the harness crashing
if ! grep -q -- "-> FAIL" "$AUTOSCALE_NEG_LOG"; then
  echo "autoscale negative control exited non-zero WITHOUT tripping the gate:" >&2
  tail -20 "$AUTOSCALE_NEG_LOG" >&2
  exit 1
fi

echo "== trace gate (paddle_tpu.trace: every request in exactly one complete"
echo "   trace, flight-recorder dumps on injected batch fault + watchdog hang,"
echo "   cost-model FLOPs within 10% of analytic, near-zero off overhead;"
echo "   MFU figures land in ci_trace_report.json)"
JAX_PLATFORMS=cpu python tools/trace_check.py --check \
  --json "${CI_ARTIFACT_DIR:-.}/ci_trace_report.json" | tail -10
echo "== trace negative control (flight recorder disabled: the gate must"
echo "   FAIL — the dump is what carries the fault context)"
TRACE_NEG_LOG="${CI_ARTIFACT_DIR:-.}/ci_trace_negative.log"
if JAX_PLATFORMS=cpu python tools/trace_check.py --check \
     --negative-control > "$TRACE_NEG_LOG" 2>&1; then
  echo "trace_check --check did NOT fail with the flight recorder disabled" >&2
  exit 1
fi
# non-zero exit must be the gate tripping, not the harness crashing
if ! grep -q -- "-> FAIL" "$TRACE_NEG_LOG"; then
  echo "trace negative control exited non-zero WITHOUT tripping the gate:" >&2
  tail -20 "$TRACE_NEG_LOG" >&2
  exit 1
fi

echo "== chaos multichip gate (resilience.distributed: kill inside one shard"
echo "   write -> serial unpublished + bit-identical resume; elastic 8->4->1"
echo "   restore; watchdog converts an injected hang, and without it the"
echo "   run provably hangs)"
python tools/chaos_check.py --check --multichip \
  --json "${CI_ARTIFACT_DIR:-.}/ci_chaos_dist_report.json"

echo "== chaos elastic gate (resilience.elastic: injected device loss at dp=8"
echo "   must auto-rescale to dp=4, resume from the last verified serial with"
echo "   an exact batch trace and a digest equal to an uninterrupted dp=4"
echo "   baseline; FLAGS_elastic=0 must die typed, retry must never absorb a"
echo "   DeviceLostError, and a capacity return upscales 4->8)"
python tools/chaos_check.py --check --elastic \
  --json "${CI_ARTIFACT_DIR:-.}/ci_chaos_elastic_report.json"

echo "== unit tests (CPU, 8 virtual devices; FLAGS_check_program on via conftest)"
python -m pytest tests/ -q -x

if [ "$MODE" = "full" ]; then
  echo "== multichip dry-run (8 virtual devices)"
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -c "import sys; sys.path.insert(0, '.'); \
               import __graft_entry__ as g; g.dryrun_multichip(8)"
fi

if [ "$MODE" = "tpu" ]; then
  echo "== on-chip smoke suite"
  PADDLE_TPU_TESTS=1 python -m pytest tests/test_tpu_smoke.py -m tpu -q
fi

echo "CI $MODE: OK"
